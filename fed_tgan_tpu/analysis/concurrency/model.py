"""Interprocedural lockset model backing the L01-L04 rules.

Pure stdlib AST -- no JAX import, millisecond startup, same contract as
the rest of jaxlint.  The unit of analysis is the class: locks are
``self.<attr>`` objects, the call graph is ``self.<method>()`` edges,
and locksets are sets of lock attribute names.  Cross-class lock flow
(e.g. a ``FleetService`` handing its lock to a ``RowPool``) is out of
scope; within a class the model is path-insensitive but call-graph
aware:

1. **Inventory** -- one walk over the class collects lock attributes
   (``threading.Lock/RLock/Condition``), which of those are reentrant,
   thread-safe containers (``queue.Queue`` family, ``deque``,
   ``Event``/``Semaphore``) and thread handles.
2. **Lexical scan** -- each method body is walked with the lexically
   held lockset threaded through ``with self._lock:`` blocks and bare
   ``.acquire()``/``.release()`` statements, recording every lock
   acquisition, shared-field access, ``self.<method>()`` call site and
   known-blocking call together with the lockset at that point.
3. **Propagation** -- a fixed point over the intra-class call graph
   computes each method's *entry* locksets: ``entry_must`` is the
   intersection over internal call sites of (caller must + lexical at
   the site) -- public methods and never-internally-called ones start
   at the empty set because outside callers hold nothing; ``entry_may``
   is the union over call sites.  ``must`` keeps L01 quiet on
   ``_locked``-suffix-style helpers; ``may`` lets L02/L03 flag hazards
   that exist on *some* call path.

Guard inference for L01: a field's guard set is every lock observed
held (must + lexical) at some non-atomic mutation of it.  Plain
rebinds (``self.x = v``) stay atomic under the GIL and never establish
nor violate a guard, which keeps the immutable-swap pattern (build a
fresh dict, publish by rebind, read without the lock) clean.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from fed_tgan_tpu.analysis.rules.base import dotted
from fed_tgan_tpu.analysis.rules.shared_state import (
    _LOCK_TYPES,
    _MUTATORS,
    _SAFE_TYPES,
    _imports_threading,
    _self_attr,
)

_RLOCK_TYPES = ("threading.RLock", "RLock",
                "threading.Condition", "Condition")
_CONDITION_TYPES = ("threading.Condition", "Condition")
_QUEUE_TYPES = ("queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
                "queue.PriorityQueue", "Queue", "SimpleQueue", "LifoQueue",
                "PriorityQueue")
_THREAD_TYPES = ("threading.Thread", "threading.Timer", "Thread", "Timer")

#: Non-mutating container-method reads that are still compound (value
#: can be torn mid-resize by a concurrent mutator).
_READER_METHODS = {"get", "items", "keys", "values", "copy"}

#: ``.attr(`` calls that block regardless of receiver type.
_BLOCKING_ATTRS = {"recv", "recvfrom", "accept", "sendall", "connect",
                   "getresponse", "get_or_build"}

#: Methods that run before (or after) any peer thread can observe the
#: object -- their accesses neither establish guards nor violate them.
_SINGLE_THREADED_METHODS = {"__init__", "__del__", "__repr__"}


@dataclass
class Access:
    field: str
    line: int
    kind: str          # "mutate" | "read"
    what: str          # human description, e.g. "item write", ".append()"
    lockset: FrozenSet[str]


@dataclass
class Acquire:
    lock: str
    line: int
    lockset: FrozenSet[str]   # lexically held just before this acquisition
    raw: bool                 # bare .acquire() call, not a with-statement
    protected: bool           # raw acquire with a try/finally release
    nonblocking: bool         # acquire(False) / acquire(blocking=False)


@dataclass
class CallSite:
    callee: str
    line: int
    lockset: FrozenSet[str]


@dataclass
class BlockingCall:
    desc: str
    line: int
    lockset: FrozenSet[str]


@dataclass
class Method:
    name: str
    line: int
    accesses: List[Access] = field(default_factory=list)
    acquires: List[Acquire] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    blocking: List[BlockingCall] = field(default_factory=list)
    entry_must: FrozenSet[str] = frozenset()
    entry_may: FrozenSet[str] = frozenset()


@dataclass
class ClassModel:
    name: str
    line: int
    locks: Set[str] = field(default_factory=set)
    rlocks: Set[str] = field(default_factory=set)      # reentrant subset
    conditions: Set[str] = field(default_factory=set)  # Condition subset
    safe: Set[str] = field(default_factory=set)
    queues: Set[str] = field(default_factory=set)
    threads: Set[str] = field(default_factory=set)
    methods: Dict[str, Method] = field(default_factory=dict)
    #: field name -> locks observed held at some non-atomic mutation
    guards: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class ModuleModel:
    classes: List[ClassModel] = field(default_factory=list)


# ------------------------------------------------------------------ scan

def _call_nonblocking(call: ast.Call) -> bool:
    """acquire(False) / acquire(blocking=False) / get(block=False) /
    get(timeout=0) -- variants that cannot block indefinitely... or at
    all, for the blocking=False family."""
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value in (False, 0):
            return True
    for kw in call.keywords:
        if kw.arg in ("blocking", "block") and \
                isinstance(kw.value, ast.Constant) and \
                kw.value.value in (False, 0):
            return True
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) and \
                kw.value.value == 0:
            return True
    return False


class _ClassScanner:
    """Builds one ClassModel: inventory, then per-method lexical scan."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.model = ClassModel(name=cls.name, line=cls.lineno)
        self._inventory()
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m = Method(name=item.name, line=item.lineno)
                # first def wins on duplicates (e.g. @property pairs)
                self.model.methods.setdefault(item.name, m)
                if self.model.methods[item.name] is m:
                    self._scan_block(item.body, frozenset(), m, frozenset())
        self._infer_guards()

    # -------------------------------------------------------- inventory

    def _inventory(self) -> None:
        mdl = self.model
        for node in ast.walk(self.cls):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            d = dotted(node.value.func) or ""
            for t in node.targets:
                attr = _self_attr(t)
                if not attr:
                    continue
                if d in _LOCK_TYPES:
                    mdl.locks.add(attr)
                    if d in _RLOCK_TYPES:
                        mdl.rlocks.add(attr)
                    if d in _CONDITION_TYPES:
                        mdl.conditions.add(attr)
                elif d in _SAFE_TYPES:
                    mdl.safe.add(attr)
                    if d in _QUEUE_TYPES:
                        mdl.queues.add(attr)
                elif d in _THREAD_TYPES:
                    mdl.threads.add(attr)

    # ----------------------------------------------------- lexical scan

    def _with_locks(self, withstmt) -> List[str]:
        out = []
        for item in withstmt.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr in self.model.locks:
                out.append(attr)
        return out

    def _raw_lock_call(self, s: ast.stmt, op: str
                       ) -> Optional[Tuple[str, ast.Call]]:
        """(lock_attr, call) when ``s`` is ``self.<lock>.<op>(...)`` as a
        bare Expr or single-target Assign statement."""
        if isinstance(s, ast.Expr):
            call = s.value
        elif isinstance(s, ast.Assign) and len(s.targets) == 1:
            call = s.value
        else:
            return None
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == op):
            return None
        attr = _self_attr(call.func.value)
        if attr in self.model.locks:
            return attr, call
        return None

    def _releases_in(self, stmts) -> Set[str]:
        out: Set[str] = set()
        for s in stmts:
            for node in ast.walk(s):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "release":
                    attr = _self_attr(node.func.value)
                    if attr in self.model.locks:
                        out.add(attr)
        return out

    def _scan_block(self, stmts, lockset: FrozenSet[str], m: Method,
                    finally_released: FrozenSet[str]) -> None:
        held = set(lockset)
        for idx, s in enumerate(stmts):
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in s.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.model.locks:
                        m.acquires.append(Acquire(
                            lock=attr, line=item.context_expr.lineno,
                            lockset=frozenset(held) | frozenset(acquired),
                            raw=False, protected=True, nonblocking=False))
                        acquired.append(attr)
                    else:
                        self._scan_exprs([item.context_expr],
                                         frozenset(held), m)
                self._scan_block(s.body, frozenset(held) | set(acquired),
                                 m, finally_released)
                continue
            raw_acq = self._raw_lock_call(s, "acquire")
            if raw_acq is not None:
                lock, call = raw_acq
                nonblocking = _call_nonblocking(call)
                protected = lock in finally_released
                if not protected and idx + 1 < len(stmts) and \
                        isinstance(stmts[idx + 1], ast.Try):
                    protected = lock in self._releases_in(
                        stmts[idx + 1].finalbody)
                m.acquires.append(Acquire(
                    lock=lock, line=s.lineno, lockset=frozenset(held),
                    raw=True, protected=protected, nonblocking=nonblocking))
                if not nonblocking:
                    held.add(lock)
                continue
            raw_rel = self._raw_lock_call(s, "release")
            if raw_rel is not None:
                held.discard(raw_rel[0])
                continue
            self._scan_stmt(s, frozenset(held), m)
            if isinstance(s, ast.Try):
                fr = frozenset(finally_released
                               | self._releases_in(s.finalbody))
                self._scan_block(s.body, frozenset(held), m, fr)
                for h in s.handlers:
                    self._scan_block(h.body, frozenset(held), m, fr)
                self._scan_block(s.orelse, frozenset(held), m, fr)
                self._scan_block(s.finalbody, frozenset(held), m,
                                 finally_released)
            else:
                for attr in ("body", "orelse"):
                    sub = getattr(s, attr, None)
                    if isinstance(sub, list) and sub and \
                            isinstance(sub[0], ast.stmt):
                        self._scan_block(sub, frozenset(held), m,
                                         finally_released)

    def _header_exprs(self, s: ast.stmt) -> Optional[List[ast.expr]]:
        """The expressions evaluated by a compound statement's header
        (its blocks are scanned separately); None for simple statements
        whose whole subtree is expression territory."""
        if isinstance(s, (ast.If, ast.While)):
            return [s.test]
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return [s.target, s.iter]
        if isinstance(s, ast.Try):
            return []
        return None

    def _scan_stmt(self, s: ast.stmt, lockset: FrozenSet[str],
                   m: Method) -> None:
        header = self._header_exprs(s)
        if header is None:
            # simple statement: targets first (mutation kinds), then the
            # full expression walk
            if isinstance(s, ast.Assign):
                for t in s.targets:
                    self._scan_target(t, lockset, m)
            elif isinstance(s, ast.AugAssign):
                t = s.target
                f = _self_attr(t) or (_self_attr(t.value)
                                      if isinstance(t, ast.Subscript) else "")
                if self._is_field(f):
                    m.accesses.append(Access(
                        field=f, line=s.lineno, kind="mutate",
                        what="read-modify-write", lockset=lockset))
            elif isinstance(s, ast.Delete):
                for t in s.targets:
                    if isinstance(t, ast.Subscript):
                        f = _self_attr(t.value)
                        if self._is_field(f):
                            m.accesses.append(Access(
                                field=f, line=s.lineno, kind="mutate",
                                what="del", lockset=lockset))
            self._scan_exprs([s], lockset, m)
        else:
            if isinstance(s, (ast.For, ast.AsyncFor)):
                f = _self_attr(s.iter)
                if self._is_field(f):
                    m.accesses.append(Access(
                        field=f, line=s.iter.lineno, kind="read",
                        what="iteration", lockset=lockset))
            self._scan_exprs(header, lockset, m)

    def _scan_target(self, t, lockset: FrozenSet[str], m: Method) -> None:
        if isinstance(t, ast.Subscript):
            f = _self_attr(t.value)
            if self._is_field(f):
                m.accesses.append(Access(
                    field=f, line=t.lineno, kind="mutate",
                    what="item write", lockset=lockset))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                self._scan_target(elt, lockset, m)

    def _is_field(self, attr: str) -> bool:
        return bool(attr) and attr not in self.model.locks \
            and attr not in self.model.safe \
            and attr not in self.model.threads

    def _scan_exprs(self, roots, lockset: FrozenSet[str],
                    m: Method) -> None:
        mdl = self.model
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Subscript) and \
                        isinstance(node.ctx, ast.Load):
                    f = _self_attr(node.value)
                    if self._is_field(f):
                        m.accesses.append(Access(
                            field=f, line=node.lineno, kind="read",
                            what="subscript read", lockset=lockset))
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute):
                    attr = func.attr
                    recv = _self_attr(func.value)
                    if recv in mdl.locks and attr in ("acquire", "release",
                                                      "locked", "notify",
                                                      "notify_all"):
                        continue
                    # self.<method>() call sites feed the call graph
                    if isinstance(func.value, ast.Name) and \
                            func.value.id in ("self", "cls"):
                        if attr in {i.name for i in self.cls.body
                                    if isinstance(i, (ast.FunctionDef,
                                                      ast.AsyncFunctionDef))}:
                            m.calls.append(CallSite(
                                callee=attr, line=node.lineno,
                                lockset=lockset))
                    self._scan_blocking(node, func, attr, recv, lockset, m)
                    if recv and self._is_field(recv):
                        if attr in _MUTATORS:
                            m.accesses.append(Access(
                                field=recv, line=node.lineno, kind="mutate",
                                what=f".{attr}()", lockset=lockset))
                        elif attr in _READER_METHODS:
                            m.accesses.append(Access(
                                field=recv, line=node.lineno, kind="read",
                                what=f".{attr}()", lockset=lockset))
                else:
                    self._scan_blocking(node, func, "", "", lockset, m)

    def _scan_blocking(self, call: ast.Call, func, attr: str, recv: str,
                       lockset: FrozenSet[str], m: Method) -> None:
        mdl = self.model
        desc = ""
        if attr in ("wait", "wait_for"):
            # Condition.wait on the condition you hold releases it while
            # waiting -- that is the correct pattern, not a blocking call
            if recv in mdl.conditions and recv in lockset:
                return
            desc = f"`.{attr}()`"
        elif attr == "join":
            if recv in mdl.threads or recv in mdl.queues:
                desc = f"`self.{recv}.join()`"
        elif attr in ("get", "put"):
            if recv in mdl.queues and not _call_nonblocking(call):
                desc = f"queue `self.{recv}.{attr}()`"
        elif attr in _BLOCKING_ATTRS:
            desc = f"`.{attr}()`"
        if not desc:
            d = dotted(func) or ""
            if d in ("time.sleep", "sleep"):
                desc = "`time.sleep()`"
            elif d.startswith("subprocess."):
                desc = f"`{d}()`"
            elif d in ("socket.create_connection",):
                desc = f"`{d}()`"
            elif d.endswith("urlopen"):
                desc = f"`{d}()`"
        if desc:
            m.blocking.append(BlockingCall(desc=desc, line=call.lineno,
                                           lockset=lockset))

    # ------------------------------------------------------ propagation

    def _infer_guards(self) -> None:
        """Fixed-point entry locksets, then per-field guard sets."""
        mdl = self.model
        methods = mdl.methods
        all_locks = frozenset(mdl.locks)
        internally_called = {c.callee for m in methods.values()
                            for c in m.calls if c.callee in methods}
        for m in methods.values():
            m.entry_may = frozenset()
            public = not m.name.startswith("_") or \
                (m.name.startswith("__") and m.name.endswith("__"))
            if public or m.name not in internally_called:
                m.entry_must = frozenset()
            else:
                m.entry_must = all_locks  # top; shrinks monotonically
        changed = True
        while changed:
            changed = False
            for caller in methods.values():
                for site in caller.calls:
                    callee = methods.get(site.callee)
                    if callee is None or callee is caller:
                        continue
                    may = caller.entry_may | site.lockset
                    if not may <= callee.entry_may:
                        callee.entry_may = callee.entry_may | may
                        changed = True
                    must = callee.entry_must & (caller.entry_must
                                                | site.lockset)
                    if must != callee.entry_must:
                        callee.entry_must = must
                        changed = True
        for m in methods.values():
            if m.name in _SINGLE_THREADED_METHODS:
                continue
            for acc in m.accesses:
                if acc.kind != "mutate":
                    continue
                eff = m.entry_must | acc.lockset
                held = eff & all_locks
                if held:
                    mdl.guards.setdefault(acc.field, set()).update(held)


# ----------------------------------------------------------------- entry

def _scoped(mod) -> bool:
    in_serve = "/serve/" in mod.relpath.replace("\\", "/")
    return in_serve or _imports_threading(mod.tree)


_CACHE: Dict[Tuple[str, int, int], ModuleModel] = {}


def analyze(mod) -> ModuleModel:
    """ModuleModel for a ``lint.ModuleInfo`` (memoized: the four L rules
    each call this per module)."""
    key = (mod.relpath, len(mod.source), hash(mod.source))
    hit = _CACHE.get(key)
    if hit is not None:
        return hit
    model = ModuleModel()
    if _scoped(mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                model.classes.append(_ClassScanner(node).model)
    if len(_CACHE) > 128:
        _CACHE.clear()
    _CACHE[key] = model
    return model


def iter_methods(model: ModuleModel
                 ) -> Iterator[Tuple[ClassModel, Method]]:
    for cls in model.classes:
        for name in sorted(cls.methods):
            yield cls, cls.methods[name]
