"""locklint rules L01-L04 over the interprocedural lockset model.

Each rule follows the jaxlint contract (``rule_id`` / ``title`` /
``hint`` / ``check(mod)``) and plugs into the ordinary driver: same
finding keys, same ``# jaxlint: disable=LXX`` escapes, same baseline
ratchet.  All four share one memoized :func:`model.analyze` pass.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Set, Tuple

from fed_tgan_tpu.analysis.concurrency.model import (
    _SINGLE_THREADED_METHODS,
    ClassModel,
    Method,
    analyze,
)

#: Read shapes L01 flags on guarded fields.  Scalar loads, subscript
#: reads and ``.get()`` are single bytecode-level dict/list ops -- atomic
#: under the GIL -- so only *iterating* reads (which a concurrent
#: mutation tears with "dict changed size during iteration" or a torn
#: view) count as compound.
_COMPOUND_READS = ("iteration", ".items()", ".keys()", ".values()")


def _sorted_methods(cls: ClassModel) -> List[Method]:
    return [cls.methods[k] for k in sorted(cls.methods)]


class UnguardedFieldRule:
    """L01 -- shared-field access without the lock that guards it.

    Interprocedural successor to the lexical J05 scan: a method's
    *entry must-lockset* (held on every internal call path) counts
    toward the guard, so a private helper only ever called under the
    lock is clean.  Two shapes:

    * a non-atomic mutation (item write / del / ``+=`` / mutator call)
      whose effective lockset misses the field's inferred guard set --
      or, for never-guarded fields, any such mutation with no lock at
      all (the J05-classic case);
    * a compound read (iteration, ``.items()``/``.keys()``/
      ``.values()``) of a field that *is* mutation-guarded elsewhere,
      reached without that guard.
    """

    rule_id = "L01"
    title = "unguarded shared field access"
    hint = ("hold the field's guard lock (`with self._lock:`) at this "
            "access, or switch the field to a thread-safe structure / "
            "immutable-swap (publish a fresh object by rebind)")

    def check(self, mod) -> Iterator:
        for cls in analyze(mod).classes:
            for m in _sorted_methods(cls):
                if m.name in _SINGLE_THREADED_METHODS:
                    continue
                for acc in m.accesses:
                    guards = cls.guards.get(acc.field, set())
                    eff = m.entry_must | acc.lockset
                    if acc.kind == "mutate":
                        if guards:
                            if eff & guards:
                                continue
                            lock = "/".join(
                                f"self.{g}" for g in sorted(guards))
                            yield (self.rule_id, acc.line,
                                   f"{acc.what} on `self.{acc.field}` "
                                   f"without its guard `{lock}` "
                                   f"(held at other mutation sites) "
                                   f"[{cls.name}.{m.name}]", self.hint)
                        elif not eff:
                            yield (self.rule_id, acc.line,
                                   f"{acc.what} on shared "
                                   f"`self.{acc.field}` without any lock "
                                   f"[{cls.name}.{m.name}]", self.hint)
                    elif acc.what in _COMPOUND_READS and guards \
                            and not (eff & guards):
                        lock = "/".join(f"self.{g}" for g in sorted(guards))
                        yield (self.rule_id, acc.line,
                               f"compound read ({acc.what}) of guarded "
                               f"`self.{acc.field}` without `{lock}` "
                               f"[{cls.name}.{m.name}]", self.hint)


class LockOrderRule:
    """L02 -- lock-order cycles and non-reentrant re-acquisition.

    Builds the per-class acquisition graph: an edge A->B every time B
    is acquired while A *may* be held (entry may-lockset + lexical,
    i.e. including locks inherited through ``self.<method>()`` call
    chains).  Any cycle is a potential cross-thread deadlock; acquiring
    a non-reentrant lock that may already be held on the path (the
    PR 9 ``submit`` holding ``_adm_lock`` -> ``_shed`` re-acquire) is a
    single-thread deadlock and is flagged at the acquisition site.
    """

    rule_id = "L02"
    title = "lock-order cycle / re-acquisition"
    hint = ("release the outer lock before this acquisition (hoist the "
            "call out of the `with` block), or impose one global "
            "acquisition order; use RLock only when re-entry is the "
            "designed behaviour")

    def check(self, mod) -> Iterator:
        for cls in analyze(mod).classes:
            yield from self._check_class(cls)

    def _check_class(self, cls: ClassModel) -> Iterator:
        edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
        for m in _sorted_methods(cls):
            for acq in m.acquires:
                may = m.entry_may | acq.lockset
                if acq.lock in may and acq.lock not in cls.rlocks:
                    yield (self.rule_id, acq.line,
                           f"`self.{acq.lock}` re-acquired while a call "
                           f"path into `{cls.name}.{m.name}` already "
                           f"holds it (non-reentrant Lock: deadlock)",
                           self.hint)
                for outer in sorted(may):
                    if outer != acq.lock:
                        edges.setdefault(
                            (outer, acq.lock),
                            (acq.line, f"{cls.name}.{m.name}"))
        for cyc_edges in self._cyclic_edges(edges):
            for (a, b), (line, where) in cyc_edges:
                order = " -> ".join(sorted({a, b} | {
                    x for e, _ in cyc_edges for x in e}))
                yield (self.rule_id, line,
                       f"lock-order cycle: `self.{b}` acquired under "
                       f"`self.{a}` in `{where}` while the reverse "
                       f"order exists elsewhere (cycle over {order})",
                       self.hint)

    def _cyclic_edges(self, edges: Dict[Tuple[str, str], Tuple[int, str]]
                      ) -> List[List[Tuple[Tuple[str, str],
                                           Tuple[int, str]]]]:
        """Edges whose endpoints share a strongly connected component of
        size >= 2, grouped per component (Tarjan, iterative)."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[Set[str]] = []
        counter = [0]

        def strongconnect(root: str) -> None:
            work = [(root, iter(adj[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(adj[w])))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    comp: Set[str] = set()
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.add(w)
                        if w == v:
                            break
                    if len(comp) >= 2:
                        sccs.append(comp)

        for node in sorted(adj):
            if node not in index:
                strongconnect(node)
        out = []
        for comp in sccs:
            comp_edges = sorted(
                (e, site) for e, site in edges.items()
                if e[0] in comp and e[1] in comp)
            if comp_edges:
                out.append(comp_edges)
        return out


class BlockingUnderLockRule:
    """L03 -- blocking call reached while a lock may be held.

    ``queue.get``/``put``, ``Event.wait``, thread ``join``,
    ``time.sleep``, ``subprocess``, socket/HTTP I/O and the
    ``ProgramCache.get_or_build`` compile path all stall every other
    thread contending for the held lock (the discipline the serving
    plane enforces by hand: sample outside the lock, build outside the
    lock, shed outside the lock).  ``Condition.wait`` on the condition
    you hold is the designed pattern and is exempt.
    """

    rule_id = "L03"
    title = "blocking call under lock"
    hint = ("move the blocking call outside the `with` block: snapshot "
            "the state you need under the lock, drop it, then block "
            "(see ProgramCache.get_or_build / RowPool._fill_chunk)")

    def check(self, mod) -> Iterator:
        for cls in analyze(mod).classes:
            for m in _sorted_methods(cls):
                if m.name in _SINGLE_THREADED_METHODS:
                    continue
                for b in m.blocking:
                    may = m.entry_may | b.lockset
                    if may:
                        locks = "/".join(f"self.{x}" for x in sorted(may))
                        yield (self.rule_id, b.line,
                               f"{b.desc} may run while holding "
                               f"`{locks}` [{cls.name}.{m.name}]",
                               self.hint)


class LockLeakRule:
    """L04 -- bare ``.acquire()`` without ``with`` or ``try/finally``.

    An exception between the acquire and the release leaks the lock and
    wedges every other thread.  Non-blocking probes
    (``acquire(False)``) are exempt -- their result is branched on, not
    held unconditionally.
    """

    rule_id = "L04"
    title = "lock acquire without release protection"
    hint = ("use `with self._lock:` (or wrap the acquire in "
            "`try: ... finally: self._lock.release()`)")

    def check(self, mod) -> Iterator:
        for cls in analyze(mod).classes:
            for m in _sorted_methods(cls):
                for acq in m.acquires:
                    if acq.raw and not acq.protected \
                            and not acq.nonblocking:
                        yield (self.rule_id, acq.line,
                               f"`self.{acq.lock}.acquire()` without a "
                               f"`with` block or try/finally release "
                               f"[{cls.name}.{m.name}]", self.hint)
