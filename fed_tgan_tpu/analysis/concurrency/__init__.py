"""locklint -- concurrency-correctness analysis for the threaded plane.

Two prongs in the jaxlint/hlolint mold:

* **Static** (this package): a pure-stdlib AST pass over the serving /
  observability modules.  ``model.py`` builds a per-class inventory of
  lock objects and shared mutable fields, scans every method for lock
  acquisitions, field accesses, self-method calls and blocking calls
  with their *lexical* locksets, then propagates locksets through the
  intra-module call graph (fixed point, like J01's 3-pass taint) to a
  per-method *entry lockset* -- ``must`` (held on every internal path,
  used to avoid false positives) and ``may`` (held on some path, used
  to catch any-path hazards).  ``rules.py`` turns the model into the
  L01-L04 findings wired through the ordinary ``analysis/lint.py``
  driver (same ``path:rule:line`` keys, ``# jaxlint: disable=LXX``
  inline escapes, ``baseline.json`` ratchet).

* **Runtime** (``analysis/lockwatch.py``, a sibling module): opt-in
  instrumented lock wrappers that record per-thread acquisition order
  into a global graph and report potential deadlocks while tests and
  benches run.

Rules:

* **L01** unguarded-shared-field-access -- a non-atomic mutation (or a
  compound read) of a field that is guarded by a lock elsewhere,
  reached on a path whose must-lockset misses that guard.  Subsumes
  the old lexical J05 scan with far fewer false positives: a private
  ``_shed``-style helper only ever called under the lock inherits the
  caller's lockset instead of being flagged.
* **L02** lock-order-cycle -- the acquisition graph (edge A->B when B
  is acquired while A may be held, including through calls) contains a
  cycle, or a non-reentrant lock is re-acquired on a path that may
  already hold it (the PR 9 ``submit`` -> ``_shed`` deadlock shape).
* **L03** blocking-call-under-lock -- ``queue.get``/``Event.wait``/
  ``subprocess``/socket I/O/``ProgramCache.get_or_build`` reached
  while any lock may be held.
* **L04** lock-leak -- a bare ``.acquire()`` not paired with a
  ``with`` block or a ``try/finally`` release.
"""

from fed_tgan_tpu.analysis.concurrency.model import analyze  # noqa: F401
from fed_tgan_tpu.analysis.concurrency.rules import (  # noqa: F401
    BlockingUnderLockRule,
    LockLeakRule,
    LockOrderRule,
    UnguardedFieldRule,
)

__all__ = ["analyze", "UnguardedFieldRule", "LockOrderRule",
           "BlockingUnderLockRule", "LockLeakRule"]
