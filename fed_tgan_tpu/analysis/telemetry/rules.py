"""obslint rules O01-O05: cross-check the extraction against the
schema registry, the budget file, and the fault-kind vocabulary.

- **O01** emit-site contract: event type missing from the registry, or
  a closed emit site missing one of the event's required fields.
- **O02** dead contract: a registry event/field no emitter produces, or
  a report/slo/watch consumer selecting an unknown event type / reading
  a field no emit site writes.
- **O03** metric-name drift: a ``counter/gauge/histogram`` call site
  absent from the catalogue, a name registered under conflicting kinds,
  an uncatalogued label key, or an unbounded label value expression
  (cardinality hazard; the ``*_CAP``-guarded client-label idiom is
  exempt).
- **O04** stale-by-construction budget: a ``budgets.json``
  ``select.metric_prefix`` no bench record writer can match, a
  ``select.backend`` outside the catalogue, or a journal-figure rule
  whose ``metric`` no ``journal_figures`` fold can produce.
- **O05** fault-spec drift: a ``kind:key=value`` fault reference in
  tests/docs/scripts that ``testing/faults.py`` cannot parse, or a
  registry ``fault_kinds`` list out of sync with ``VALID_KINDS``.

Findings reuse the jaxlint ``Finding``/baseline/suppression machinery;
JSON-file findings (schema.json, budgets.json) are located by scanning
the raw text for the offending key, so they are clickable too.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fed_tgan_tpu.analysis.lint import (
    Finding,
    LintError,
    REPO_ROOT,
    _SUPPRESS_RE,
)
from fed_tgan_tpu.analysis.telemetry.extract import (
    Extraction,
    MetricSite,
    extract_repo,
)
from fed_tgan_tpu.analysis.telemetry.schema import (
    DEFAULT_SCHEMA_PATH,
    load_schema,
)

__all__ = ["RULE_IDS", "RULE_TITLES", "run_telemetry"]

RULE_IDS = ("O01", "O02", "O03", "O04", "O05")

RULE_TITLES = {
    "O01": "emit site outside the event registry",
    "O02": "dead telemetry contract",
    "O03": "metric-name drift",
    "O04": "stale-by-construction budget selector",
    "O05": "fault-spec drift",
}

_HINTS = {
    "O01": "add the event/field to obs/schema.json (--schema-update "
           "discovers it) or fix the emit site",
    "O02": "remove the dead registry entry / consumer read, or add the "
           "missing emitter",
    "O03": "catalogue the metric in obs/schema.json, or bound the label "
           "with the *_CAP idiom",
    "O04": "fix the budgets.json selector to a prefix a producer can "
           "match, or delete the rule",
    "O05": "use a kind testing/faults.py parses (see VALID_KINDS)",
}


def _rel(path: Path) -> str:
    try:
        return path.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.as_posix()


def _json_line(text: str, needle: str) -> int:
    for i, line in enumerate(text.splitlines(), 1):
        if needle in line:
            return i
    return 1


def _finding(rule: str, path: str, line: int, message: str) -> Finding:
    return Finding(rule=rule, path=path, line=line, message=message,
                   hint=_HINTS[rule])


# ------------------------------------------------------------ matching


def _event_known_fields(ev: dict) -> Set[str]:
    return set(ev["required"]) | set(ev["optional"]) | set(ev["external"])


def _match_metric(metrics: dict, site: MetricSite) -> Optional[str]:
    if not site.dynamic and site.name in metrics:
        return site.name
    for key in metrics:
        if not key.endswith("*"):
            continue
        p = key[:-1]
        if site.name.startswith(p) or (site.dynamic and p.startswith(
                site.name)):
            return key
    return None


def _match_prefix(sel: str, producers: Sequence[str]) -> bool:
    """Bidirectional prefix match: the selector restricts record
    ``metric`` strings, producers are static names/prefixes that gain
    runtime suffixes (bgm/rpp tags), so either side may be longer."""
    for p in producers:
        p = p[:-1] if p.endswith("*") else p
        if p.startswith(sel) or sel.startswith(p):
            return True
    return False


def _match_figure(metric: str, figures: Sequence[str]) -> bool:
    for f in figures:
        if f.endswith("*"):
            if metric.startswith(f[:-1]):
                return True
        elif metric == f:
            return True
    return False


# --------------------------------------------------------------- rules


def _check_emits(ex: Extraction, schema: dict,
                 out: List[Finding]) -> int:
    covered = 0
    events = schema["events"]
    for site in ex.emits:
        ev = events.get(site.event)
        if ev is None:
            out.append(_finding(
                "O01", site.path, site.line,
                f"emit site for unknown event type {site.event!r} "
                f"(not in obs/schema.json)"))
            continue
        covered += 1
        if site.open:
            continue
        missing = sorted(set(ev["required"]) - set(site.fields))
        if missing:
            out.append(_finding(
                "O01", site.path, site.line,
                f"emit site for {site.event!r} missing required "
                f"field(s) {', '.join(missing)}"))
    return covered


def _check_dead_contracts(ex: Extraction, schema: dict,
                          out: List[Finding], repo_wide: bool) -> None:
    events = schema["events"]
    by_event: Dict[str, list] = {}
    for site in ex.emits:
        by_event.setdefault(site.event, []).append(site)
    if repo_wide:
        schema_path = DEFAULT_SCHEMA_PATH
        text = schema_path.read_text() if schema_path.exists() else ""
        rel = _rel(schema_path)
        for name, ev in sorted(events.items()):
            sites = by_event.get(name, [])
            if not sites:
                out.append(_finding(
                    "O02", rel, _json_line(text, f'"{name}"'),
                    f"registry event {name!r} has no emit site in the "
                    "tree (dead contract)"))
                continue
            if any(s.open for s in sites) or ev["open"]:
                continue
            written = {f for s in sites for f in s.fields}
            dead = sorted((set(ev["required"]) | set(ev["optional"]))
                          - written)
            if dead:
                out.append(_finding(
                    "O02", rel, _json_line(text, f'"{name}"'),
                    f"registry field(s) {', '.join(dead)} of event "
                    f"{name!r} are written by no emit site (move to "
                    f"'external' or delete)"))
    for flt in ex.filters:
        if flt.event not in events:
            out.append(_finding(
                "O02", flt.path, flt.line,
                f"consumer selects unknown event type {flt.event!r}"))
    for read in ex.reads:
        ev = events.get(read.event)
        if ev is None:
            continue  # the filter site already carries the finding
        if ev["open"]:
            continue
        written = {f for s in by_event.get(read.event, ())
                   for f in s.fields}
        if read.field not in _event_known_fields(ev) | written:
            out.append(_finding(
                "O02", read.path, read.line,
                f"consumer reads field {read.field!r} of event "
                f"{read.event!r} that no emit site writes"))


def _check_metrics(ex: Extraction, schema: dict,
                   out: List[Finding]) -> int:
    covered = 0
    metrics = schema["metrics"]
    kind_by_name: Dict[str, MetricSite] = {}
    for site in ex.metrics:
        key = _match_metric(metrics, site)
        if key is None:
            name = site.name + ("*" if site.dynamic else "")
            out.append(_finding(
                "O03", site.path, site.line,
                f"{site.kind} call site {name!r} not in the metric "
                "catalogue"))
        else:
            covered += 1
            entry = metrics[key]
            if entry["kind"] != site.kind:
                out.append(_finding(
                    "O03", site.path, site.line,
                    f"metric {site.name!r} registered as {site.kind} "
                    f"but catalogued as {entry['kind']}"))
            unknown = sorted(set(site.labels) - set(entry["labels"]))
            if unknown:
                out.append(_finding(
                    "O03", site.path, site.line,
                    f"metric {site.name!r} uses uncatalogued label "
                    f"key(s) {', '.join(unknown)}"))
        prev = kind_by_name.get(site.name)
        if prev is not None and prev.kind != site.kind:
            out.append(_finding(
                "O03", site.path, site.line,
                f"metric {site.name!r} registered as {site.kind} here "
                f"but as {prev.kind} at {prev.path}:{prev.line}"))
        else:
            kind_by_name.setdefault(site.name, site)
        for key_ in site.unbounded:
            out.append(_finding(
                "O03", site.path, site.line,
                f"label {key_!r} of metric {site.name!r} takes an "
                "unbounded value expression (cardinality hazard)"))
    return covered


def _check_budgets(ex: Extraction, schema: dict, budgets_path: Path,
                   out: List[Finding]) -> None:
    try:
        text = budgets_path.read_text()
        doc = json.loads(text)
    except (OSError, json.JSONDecodeError) as exc:
        raise LintError(f"bad budgets {budgets_path}: {exc}") from exc
    rules = doc.get("budgets")
    if not isinstance(rules, list):
        raise LintError(f"budgets {budgets_path}: expected "
                        '{"budgets": [...]} document')
    rel = _rel(budgets_path)
    bench = sorted({(b.name + "*" if b.dynamic else b.name)
                    for b in ex.bench_metrics}
                   | set(schema["bench_metrics"]))
    figures = sorted({(f.key + "*" if f.prefix else f.key)
                      for f in ex.figures} | set(schema["figures"]))
    backends = set(schema["backends"])
    for rule in rules:
        if not isinstance(rule, dict):
            continue
        name = str(rule.get("name", rule.get("metric", "?")))
        line = _json_line(text, f'"{name}"')
        select = rule.get("select") or {}
        sel_prefix = select.get("metric_prefix")
        if sel_prefix is not None and not _match_prefix(
                str(sel_prefix), bench):
            out.append(_finding(
                "O04", rel, line,
                f"budget {name!r}: select.metric_prefix "
                f"{sel_prefix!r} matches no known bench metric "
                "producer (stale by construction)"))
        backend = select.get("backend")
        if backend is not None and str(backend) not in backends \
                and not str(backend).startswith("plugin:"):
            out.append(_finding(
                "O04", rel, line,
                f"budget {name!r}: select.backend {backend!r} is not "
                f"a catalogued backend {sorted(backends)}"))
        if sel_prefix is None:
            metric = str(rule.get("metric", ""))
            if metric and not _match_figure(metric, figures):
                out.append(_finding(
                    "O04", rel, line,
                    f"budget {name!r}: figure {metric!r} matches no "
                    "journal_figures fold (stale by construction)"))


def _check_faults(ex: Extraction, schema: dict,
                  out: List[Finding], repo_wide: bool) -> None:
    kinds = set(ex.fault_kinds)
    if not kinds:
        return
    for ref in ex.fault_refs:
        if ref.kind not in kinds:
            out.append(_finding(
                "O05", ref.path, ref.line,
                f"fault spec {ref.spec!r}: kind {ref.kind!r} is not "
                "parseable by testing/faults.py"))
    if repo_wide and set(schema["fault_kinds"]) != kinds:
        schema_path = DEFAULT_SCHEMA_PATH
        text = schema_path.read_text() if schema_path.exists() else ""
        missing = sorted(kinds - set(schema["fault_kinds"]))
        extra = sorted(set(schema["fault_kinds"]) - kinds)
        out.append(_finding(
            "O05", _rel(schema_path), _json_line(text, '"fault_kinds"'),
            "registry fault_kinds out of sync with "
            f"testing/faults.VALID_KINDS (missing {missing}, "
            f"extra {extra})"))


# -------------------------------------------------------------- driver


def _suppressed(lines: Dict[str, List[str]], f: Finding) -> bool:
    src = lines.get(f.path)
    if src is None:
        return False
    for ln in (f.line, f.line - 1):
        if 1 <= ln <= len(src):
            m = _SUPPRESS_RE.search(src[ln - 1])
            if m:
                ids = m.group("ids")
                if ids is None or f.rule in {
                        s.strip() for s in ids.split(",")}:
                    return True
    return False


def run_telemetry(paths: Optional[Sequence] = None,
                  schema_path: Optional[Path] = None,
                  budgets_path: Optional[Path] = None,
                  rules: Optional[Sequence[str]] = None,
                  ) -> Tuple[List[Finding], Dict[str, int]]:
    """Run the O01-O05 telemetry rules.

    ``paths=None`` is the repo-wide gate (enables the registry-side O02
    dead-contract checks, the O04 budget audit against the packaged
    ``obs/budgets.json``, and the O05 registry-sync check).  Explicit
    ``paths`` scope the emit/metric/consumer checks to those files;
    ``budgets_path`` forces the O04 audit against that file either way.
    Returns ``(findings, coverage)`` where coverage counts how many
    discovered emit / metric call sites the registry covers.
    """
    repo_wide = paths is None
    ex = extract_repo(paths)
    schema = load_schema(schema_path)
    raw: List[Finding] = []
    emit_covered = _check_emits(ex, schema, raw)
    _check_dead_contracts(ex, schema, raw, repo_wide)
    metric_covered = _check_metrics(ex, schema, raw)
    if budgets_path is not None or repo_wide:
        from fed_tgan_tpu.obs.slo import default_budgets_path
        _check_budgets(ex, schema,
                       Path(budgets_path or default_budgets_path()), raw)
    _check_faults(ex, schema, raw, repo_wide)

    wanted = set(rules) if rules else None
    findings: List[Finding] = []
    seen: Set[str] = set()
    for f in raw:
        if wanted is not None and f.rule not in wanted:
            continue
        if _suppressed(ex.lines, f) or f.key in seen:
            continue
        seen.add(f.key)
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    coverage = {
        "emit_sites": len(ex.emits),
        "emit_sites_covered": emit_covered,
        "metric_sites": len(ex.metrics),
        "metric_sites_covered": metric_covered,
    }
    return findings, coverage
