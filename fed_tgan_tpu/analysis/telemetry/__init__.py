"""obslint -- the telemetry-contract analysis prong.

Fourth member of the analysis family: jaxlint (AST-of-JAX, J01-J06),
hlolint (lowered-IR contracts), locklint (concurrency, L01-L04), and
obslint (telemetry contracts, O01-O05).  The static prong cross-checks
every journal emit site, metric get-or-create site, obs consumer read,
budget selector, and fault-spec reference against the checked-in
registry ``fed_tgan_tpu/obs/schema.json``; the runtime prong is the
``validate=True`` mode on :class:`fed_tgan_tpu.obs.journal.RunJournal`.

CLI: ``python -m fed_tgan_tpu.analysis --telemetry [--schema-update]``.
"""

from fed_tgan_tpu.analysis.telemetry.extract import Extraction, extract_repo
from fed_tgan_tpu.analysis.telemetry.rules import (
    RULE_IDS,
    RULE_TITLES,
    run_telemetry,
)
from fed_tgan_tpu.analysis.telemetry.schema import (
    DEFAULT_SCHEMA_PATH,
    generate_schema,
    load_schema,
    save_schema,
)

__all__ = [
    "DEFAULT_SCHEMA_PATH",
    "Extraction",
    "RULE_IDS",
    "RULE_TITLES",
    "extract_repo",
    "generate_schema",
    "load_schema",
    "run_telemetry",
    "save_schema",
]
