"""The telemetry schema registry: load / generate / merge
``fed_tgan_tpu/obs/schema.json``.

The registry is *generated once* (``--schema-update``) from the static
extraction, then hand-curated: required fields get trimmed to what every
producer (and the legacy journals tests replay) actually guarantees,
legacy/externally-merged fields move to ``external``, and events whose
shapes the AST cannot enumerate stay ``open``.  Merging never deletes a
curated entry -- new discoveries land as additions, exactly like the
hlolint ``--contracts-update`` ratchet reset, and the obslint O-rules
plus the runtime validator then hold the tree to the registry.

Registry shape::

    {"version": 1,
     "events": {"<type>": {
         "required": [...],   # every emit must carry these
         "optional": [...],   # statically discovered kw fields
         "external": [...],   # written outside the static view
                              # (legacy journals, merged rank streams)
         "open": bool,        # emitters may attach unlisted fields
         "producers": ["<repo-relative path>", ...]}},
     "metrics": {"<name or prefix*>": {
         "kind": "counter|gauge|histogram",
         "labels": [...], "producers": [...]}},
     "bench_metrics": [...],  # record "metric" literals (prefix if *)
     "figures": [...],        # journal-fold figure keys (prefix if *)
     "backends": [...],       # values select.backend may name
     "fault_kinds": [...]}    # mirror of testing/faults.VALID_KINDS

A trailing ``*`` marks a prefix entry wherever names may carry a
dynamic tail (f-string metric names, bench workload tags).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from fed_tgan_tpu.analysis.lint import LintError, PKG_ROOT
from fed_tgan_tpu.analysis.telemetry.extract import Extraction

__all__ = [
    "DEFAULT_SCHEMA_PATH",
    "generate_schema",
    "load_schema",
    "save_schema",
]

DEFAULT_SCHEMA_PATH = PKG_ROOT / "obs" / "schema.json"

SCHEMA_DOC_VERSION = 1

_EVENT_KEYS = ("required", "optional", "external", "open", "producers")


def load_schema(path: Optional[Path] = None) -> dict:
    path = Path(path) if path else DEFAULT_SCHEMA_PATH
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise LintError(f"bad schema {path}: {exc}") from exc
    if not isinstance(doc, dict) or not isinstance(doc.get("events"), dict):
        raise LintError(f"schema {path}: expected an object with 'events'")
    for key in ("metrics",):
        if not isinstance(doc.get(key), dict):
            doc[key] = {}
    for key in ("bench_metrics", "figures", "backends", "fault_kinds"):
        if not isinstance(doc.get(key), list):
            doc[key] = []
    for name, ev in doc["events"].items():
        if not isinstance(ev, dict):
            raise LintError(f"schema {path}: event {name!r} must be an "
                            "object")
        for k in ("required", "optional", "external", "producers"):
            ev.setdefault(k, [])
        ev.setdefault("open", False)
    for name, m in doc["metrics"].items():
        if not isinstance(m, dict) or "kind" not in m:
            raise LintError(f"schema {path}: metric {name!r} needs a "
                            "'kind'")
        m.setdefault("labels", [])
        m.setdefault("producers", [])
    return doc


def save_schema(schema: dict, path: Optional[Path] = None) -> Path:
    path = Path(path) if path else DEFAULT_SCHEMA_PATH
    path.write_text(json.dumps(schema, indent=2, sort_keys=True) + "\n")
    return path


def _metric_key(name: str, dynamic: bool) -> str:
    return f"{name}*" if dynamic else name


def generate_schema(ex: Extraction,
                    existing: Optional[dict] = None
                    ) -> Tuple[dict, List[str]]:
    """Merge the extraction into ``existing`` (never deleting curated
    entries); returns ``(schema, added-entry descriptions)``."""
    schema = existing or {
        "version": SCHEMA_DOC_VERSION,
        "comment": ("telemetry contract registry (obslint): journal event "
                    "schemas, metric-name catalogue, budget-selector "
                    "producers.  Generated via `python -m "
                    "fed_tgan_tpu.analysis --telemetry --schema-update`, "
                    "then hand-curated; merging adds, never deletes."),
        "events": {}, "metrics": {}, "bench_metrics": [], "figures": [],
        "backends": ["cpu", "gpu", "tpu"], "fault_kinds": [],
    }
    added: List[str] = []

    by_event: Dict[str, list] = {}
    for site in ex.emits:
        by_event.setdefault(site.event, []).append(site)
    for event in sorted(by_event):
        sites = by_event[event]
        closed = [s for s in sites if not s.open]
        union = sorted({f for s in sites for f in s.fields})
        producers = sorted({s.path for s in sites})
        entry = schema["events"].get(event)
        if entry is None:
            required = sorted(
                set.intersection(*[set(s.fields) for s in closed])
            ) if closed else []
            schema["events"][event] = {
                "required": required,
                "optional": sorted(set(union) - set(required)),
                "external": [],
                "open": any(s.open for s in sites),
                "producers": producers,
            }
            added.append(f"event {event}")
        else:
            known = set(entry["required"]) | set(entry["optional"]) \
                | set(entry["external"])
            new_fields = sorted(set(union) - known)
            if new_fields:
                entry["optional"] = sorted(
                    set(entry["optional"]) | set(new_fields))
                added.append(f"event {event} field(s) "
                             f"{', '.join(new_fields)}")
            if sorted(set(entry["producers"]) | set(producers)) \
                    != sorted(entry["producers"]):
                entry["producers"] = sorted(
                    set(entry["producers"]) | set(producers))

    for site in ex.metrics:
        key = _metric_key(site.name, site.dynamic)
        entry = schema["metrics"].get(key)
        if entry is None:
            schema["metrics"][key] = {
                "kind": site.kind,
                "labels": sorted(site.labels),
                "producers": [site.path],
            }
            added.append(f"metric {key}")
        else:
            if set(site.labels) - set(entry["labels"]):
                entry["labels"] = sorted(
                    set(entry["labels"]) | set(site.labels))
                added.append(f"metric {key} label(s) "
                             f"{', '.join(sorted(site.labels))}")
            if site.path not in entry["producers"]:
                entry["producers"] = sorted(
                    set(entry["producers"]) | {site.path})

    bench = sorted({_metric_key(b.name, b.dynamic)
                    for b in ex.bench_metrics})
    new_bench = sorted(set(bench) - set(schema["bench_metrics"]))
    if new_bench:
        schema["bench_metrics"] = sorted(
            set(schema["bench_metrics"]) | set(new_bench))
        added.extend(f"bench metric {b}" for b in new_bench)

    figures = sorted({_metric_key(f.key, f.prefix) for f in ex.figures})
    new_figs = sorted(set(figures) - set(schema["figures"]))
    if new_figs:
        schema["figures"] = sorted(set(schema["figures"]) | set(new_figs))
        added.extend(f"figure {f}" for f in new_figs)

    new_kinds = sorted(set(ex.fault_kinds) - set(schema["fault_kinds"]))
    if new_kinds:
        schema["fault_kinds"] = sorted(
            set(schema["fault_kinds"]) | set(new_kinds))
        added.extend(f"fault kind {k}" for k in new_kinds)
    return schema, added
