"""obslint extraction: every telemetry contract surface, statically.

Pure stdlib + AST (importing this never imports JAX).  One walk over the
package (plus ``bench.py`` and ``scripts/``) collects the four surfaces
the O01-O05 rules cross-check against ``obs/schema.json``:

- **emit sites** -- every ``emit("type", field=...)`` call, threading
  through the ``from fed_tgan_tpu.obs.journal import emit as _emit_event``
  aliases used across ``train/``, ``serve/``, ``runtime/`` and
  ``federation/elastic.py``, plus ``journal.emit(...)`` attribute calls
  and the raw ``{"ts": ..., "type": "..."}`` dict-literal append in
  ``obs/watch.py``.  ``**{...literal...}`` splats contribute their
  constant keys; any other splat marks the site *open* (it may attach
  fields the AST cannot see).
- **metric sites** -- every ``counter/gauge/histogram`` get-or-create
  call (by registry import alias or terminal attribute), recording the
  static name (or f-string prefix), kind, label keys (one assignment hop
  is resolved), and which label values look unbounded -- ``str(x)`` or
  an f-string of a variable with no ``*_CAP`` guard in the enclosing
  function, the cardinality hazard O03 flags.  The 64-label client-cap
  idiom in ``train/federated.py`` is the exempt pattern.
- **consumer reads** -- which event fields ``obs/report.py`` /
  ``slo.py`` / ``watch.py`` actually read, via the two consumer idioms:
  (A) ``rounds = [e for e in events if e.get("type") == "round"]``
  followed by iteration/``next()`` reads (one call-threading hop into
  module-local helpers like ``_clients_section``), and
  (B) ``kind = ev.get("type")`` + ``if kind == "...":`` branch-scoped
  reads (the ``journal_figures`` / ``_WatchState.fold`` shape).
- **figure + bench-metric producers** -- the figure keys/prefixes
  ``journal_figures`` can fold and the ``"metric"`` literals bench
  record writers stamp, which O04 checks budget selectors against.

Fault-spec references (O05) come from a text scan over tests/docs/
scripts for ``kind:key=value`` shaped strings whose key set overlaps
the fault-arg vocabulary; ``testing/faults.py``'s ``VALID_KINDS`` tuple
is read from its AST, never imported.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fed_tgan_tpu.analysis.lint import (
    ModuleInfo,
    PKG_ROOT,
    REPO_ROOT,
    iter_py_files,
    parse_module,
)

__all__ = [
    "BenchMetric",
    "ConsumerFilter",
    "ConsumerRead",
    "EmitSite",
    "Extraction",
    "FaultRef",
    "FigureKey",
    "MetricSite",
    "extract_repo",
]

REGISTRY_FUNCS = ("counter", "gauge", "histogram")

#: argument-key vocabulary of ``testing/faults.py`` spec strings -- a
#: ``kind:key=value`` match must use at least one of these to count as a
#: fault-spec reference (keeps the O05 text scan away from URLs, YAML,
#: and prose that merely contains a colon).
FAULT_ARG_KEYS = frozenset({
    "rank", "round", "ms", "after", "save", "nth", "factor",
    "client", "count", "delay", "shift", "until",
})

_CAP_RE = re.compile(r"[A-Z_]*CAP\b")
_FAULT_REF_RE = re.compile(
    r"\b([a-z][a-z0-9_]{2,}):((?:[a-z_]+=[-\w./]+)(?:,[a-z_]+=[-\w./]+)*)")
_FIGURE_KEY_RE = re.compile(r"[a-z0-9_]+(?:/[a-z0-9_\[\]]+)*/?")


@dataclass(frozen=True)
class EmitSite:
    event: str
    fields: Tuple[str, ...]
    open: bool  # a non-literal ``**splat`` may attach unseen fields
    path: str
    line: int


@dataclass(frozen=True)
class MetricSite:
    name: str      # full name, or the static prefix when dynamic
    dynamic: bool  # f-string / concat tail the AST cannot resolve
    kind: str      # counter | gauge | histogram
    labels: Tuple[str, ...]
    unbounded: Tuple[str, ...]  # label keys with unbounded value exprs
    path: str
    line: int


@dataclass(frozen=True)
class ConsumerFilter:
    """A consumer site *selecting* an event type (list-comp filter or
    dispatch branch) -- checked against the schema even when no field
    of the selected events is read."""
    event: str
    path: str
    line: int


@dataclass(frozen=True)
class ConsumerRead:
    event: str
    field: str
    path: str
    line: int


@dataclass(frozen=True)
class BenchMetric:
    name: str      # full metric literal, or static prefix when dynamic
    dynamic: bool
    path: str
    line: int


@dataclass(frozen=True)
class FigureKey:
    key: str
    prefix: bool  # True: journal fold produces ``key`` + a dynamic tail


@dataclass(frozen=True)
class FaultRef:
    kind: str
    spec: str
    path: str
    line: int


@dataclass
class Extraction:
    emits: List[EmitSite] = field(default_factory=list)
    metrics: List[MetricSite] = field(default_factory=list)
    filters: List[ConsumerFilter] = field(default_factory=list)
    reads: List[ConsumerRead] = field(default_factory=list)
    bench_metrics: List[BenchMetric] = field(default_factory=list)
    figures: List[FigureKey] = field(default_factory=list)
    fault_kinds: Tuple[str, ...] = ()
    fault_refs: List[FaultRef] = field(default_factory=list)
    #: relpath -> source lines, for the shared suppression-comment check
    lines: Dict[str, List[str]] = field(default_factory=dict)


# -------------------------------------------------------------- helpers


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _static_name(node) -> Optional[Tuple[str, bool]]:
    """Resolve a metric/bench name expr -> (static prefix, dynamic?)."""
    s = _const_str(node)
    if s is not None:
        return s, False
    if isinstance(node, ast.JoinedStr):
        prefix: List[str] = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix.append(part.value)
            else:
                return "".join(prefix), True
        return "".join(prefix), False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _static_name(node.left)
        if left is None:
            return None
        lname, ldyn = left
        if ldyn:
            return lname, True
        right = _static_name(node.right)
        if right is None:
            return lname, True
        rname, rdyn = right
        return lname + rname, rdyn
    return None


def _get_field(node, varname: str) -> Optional[str]:
    """``var.get("f")`` / ``var["f"]`` -> "f" (None when not a read)."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == varname and node.args):
        return _const_str(node.args[0])
    if (isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name)
            and node.value.id == varname):
        sl = node.slice
        return _const_str(sl)
    return None


def _type_filter(test) -> Set[str]:
    """Event types selected by an if-expression like
    ``e.get("type") == "round"`` / ``e["type"] in ("a", "b")``.
    BoolOp(And) operands are scanned too."""
    types: Set[str] = set()
    nodes = test.values if isinstance(test, ast.BoolOp) else [test]
    for t in nodes:
        if not (isinstance(t, ast.Compare) and len(t.ops) == 1):
            continue
        left = t.left
        is_type_read = (
            isinstance(left, ast.Call)
            and isinstance(left.func, ast.Attribute)
            and left.func.attr == "get" and left.args
            and _const_str(left.args[0]) == "type"
        ) or (
            isinstance(left, ast.Subscript)
            and _const_str(left.slice) == "type"
        )
        if not is_type_read:
            continue
        comp = t.comparators[0]
        if isinstance(t.ops[0], ast.Eq):
            s = _const_str(comp)
            if s is not None:
                types.add(s)
        elif isinstance(t.ops[0], ast.In) and isinstance(
                comp, (ast.Tuple, ast.List, ast.Set)):
            for el in comp.elts:
                s = _const_str(el)
                if s is not None:
                    types.add(s)
    return types


# ----------------------------------------------------- per-module walk


class _ModuleExtractor:
    def __init__(self, mod: ModuleInfo, out: Extraction,
                 bench_mode: bool = False,
                 consumer_mode: bool = False) -> None:
        self.mod = mod
        self.out = out
        self.bench_mode = bench_mode
        self.consumer_mode = consumer_mode
        self.emit_names: Set[str] = set()
        self.reg_names: Dict[str, str] = {}
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.fn_defs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in mod.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        #: (fn name, param name) -> event types threaded from call sites
        self.param_types: Dict[Tuple[str, str], Set[str]] = {}

    # -- imports -------------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.ImportFrom) or not node.module:
                continue
            m = node.module
            if m.endswith("obs.journal") or m == "journal":
                for alias in node.names:
                    if alias.name == "emit":
                        self.emit_names.add(alias.asname or alias.name)
            if (m.endswith("obs.registry") or m.endswith(".obs")
                    or m in ("obs", "registry")):
                for alias in node.names:
                    if alias.name in REGISTRY_FUNCS:
                        self.reg_names[alias.asname or alias.name] = \
                            alias.name

    # -- emit sites ----------------------------------------------------

    def _emit_site(self, call: ast.Call) -> None:
        f = call.func
        is_emit = (isinstance(f, ast.Name) and f.id in self.emit_names) or \
            (isinstance(f, ast.Attribute) and f.attr == "emit")
        if not is_emit or not call.args:
            return
        etype = _const_str(call.args[0])
        if etype is None:
            return
        fields: Set[str] = set()
        open_ = False
        for kw in call.keywords:
            if kw.arg is not None:
                fields.add(kw.arg)
            elif (isinstance(kw.value, ast.Dict)
                  and all(k is not None and _const_str(k) is not None
                          for k in kw.value.keys)):
                fields.update(_const_str(k) for k in kw.value.keys)
            else:
                open_ = True
        self.out.emits.append(EmitSite(
            event=etype, fields=tuple(sorted(fields)), open=open_,
            path=self.mod.relpath, line=call.lineno))

    def _dict_emit_site(self, node: ast.Dict) -> None:
        """Raw journal-line dict literal (the ``obs watch`` breach
        append): both ``"ts"`` and a constant ``"type"`` present."""
        keymap = {}
        for k, v in zip(node.keys, node.values):
            ks = _const_str(k) if k is not None else None
            if ks is not None:
                keymap[ks] = v
        if "ts" not in keymap or "type" not in keymap:
            return
        etype = _const_str(keymap["type"])
        if etype is None:
            return
        fields = tuple(sorted(k for k in keymap if k not in ("ts", "type")))
        self.out.emits.append(EmitSite(
            event=etype, fields=fields, open=True,
            path=self.mod.relpath, line=node.lineno))

    # -- metric sites --------------------------------------------------

    def _metric_site(self, call: ast.Call) -> None:
        f = call.func
        kind = None
        if isinstance(f, ast.Name):
            kind = self.reg_names.get(f.id)
        elif isinstance(f, ast.Attribute) and f.attr in REGISTRY_FUNCS:
            kind = f.attr
        if kind is None or not call.args:
            return
        nm = _static_name(call.args[0])
        if nm is None:
            return
        name, dynamic = nm
        labels: Tuple[str, ...] = ()
        unbounded: List[str] = []
        for kw in call.keywords:
            if kw.arg != "labels":
                continue
            d = self._resolve_dict(kw.value, call)
            if d is None:
                continue
            keys = []
            for k, v in zip(d.keys, d.values):
                ks = _const_str(k) if k is not None else None
                if ks is None:
                    continue
                keys.append(ks)
                if self._value_unbounded(v) and not self._cap_exempt(call):
                    unbounded.append(ks)
            labels = tuple(sorted(keys))
        self.out.metrics.append(MetricSite(
            name=name, dynamic=dynamic, kind=kind, labels=labels,
            unbounded=tuple(sorted(unbounded)),
            path=self.mod.relpath, line=call.lineno))

    def _resolve_dict(self, expr, ctx) -> Optional[ast.Dict]:
        if isinstance(expr, ast.Dict):
            return expr
        if isinstance(expr, ast.Name):
            fn = self._enclosing_function(ctx)
            scope = fn if fn is not None else self.mod.tree
            for node in ast.walk(scope):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == expr.id
                        and isinstance(node.value, ast.Dict)):
                    return node.value
        return None

    @staticmethod
    def _value_unbounded(v) -> bool:
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id == "str" and v.args
                and not isinstance(v.args[0], ast.Constant)):
            return True
        if isinstance(v, ast.JoinedStr) and any(
                isinstance(p, ast.FormattedValue) for p in v.values):
            return True
        return False

    def _enclosing_function(self, node):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def _cap_exempt(self, node) -> bool:
        """The bounded-label idiom: the enclosing function guards the
        loop with a ``*_CAP`` comparison (``if i >= self._LEDGER_
        LABEL_CAP: continue``) before labeling by index."""
        fn = self._enclosing_function(node)
        if fn is None:
            return False
        end = getattr(fn, "end_lineno", fn.lineno)
        seg = "\n".join(self.mod.lines[fn.lineno - 1:end])
        return bool(_CAP_RE.search(seg))

    # -- consumer reads ------------------------------------------------

    def _consumer_pass(self, collect: bool) -> None:
        scopes = [self.mod.tree] + list(self.fn_defs.values())
        for scope in scopes:
            self._consumer_scope(scope, collect)

    def _consumer_scope(self, scope, collect: bool) -> None:
        listmap: Dict[str, Set[str]] = {}
        scalarmap: Dict[str, Set[str]] = {}
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for arg in scope.args.args + scope.args.kwonlyargs:
                seeded = self.param_types.get((scope.name, arg.arg))
                if seeded:
                    listmap[arg.arg] = set(seeded)
        own_nodes = self._scope_nodes(scope)
        # 1. filter assigns + dispatch-variable discovery
        for node in own_nodes:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            target = node.targets[0].id
            value = node.value
            comp = None
            scalar = False
            if isinstance(value, (ast.ListComp, ast.GeneratorExp)):
                comp = value
            elif (isinstance(value, ast.Call)
                  and isinstance(value.func, ast.Name)
                  and value.func.id == "next" and value.args
                  and isinstance(value.args[0], ast.GeneratorExp)):
                comp = value.args[0]
                scalar = True
            if comp is not None and len(comp.generators) == 1:
                types: Set[str] = set()
                for cond in comp.generators[0].ifs:
                    types |= _type_filter(cond)
                if types:
                    if collect:
                        for t in sorted(types):
                            self.out.filters.append(ConsumerFilter(
                                event=t, path=self.mod.relpath,
                                line=node.lineno))
                    (scalarmap if scalar else listmap)[target] = types
        # 2. one-hop call threading into module-local helpers
        if not collect:
            for node in own_nodes:
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in self.fn_defs):
                    continue
                fn = self.fn_defs[node.func.id]
                params = [a.arg for a in fn.args.args]
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name) and a.id in listmap \
                            and i < len(params):
                        self.param_types.setdefault(
                            (fn.name, params[i]), set()).update(
                                listmap[a.id])
                for kw in node.keywords:
                    if kw.arg and isinstance(kw.value, ast.Name) \
                            and kw.value.id in listmap:
                        self.param_types.setdefault(
                            (fn.name, kw.arg), set()).update(
                                listmap[kw.value.id])
        if not collect:
            return
        # 3. iteration reads over list-vars
        for node in own_nodes:
            iters = []
            if isinstance(node, ast.For) and isinstance(node.iter, ast.Name) \
                    and node.iter.id in listmap \
                    and isinstance(node.target, ast.Name):
                iters.append((node.target.id, listmap[node.iter.id],
                              node.body))
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.SetComp, ast.DictComp)):
                for gen in node.generators:
                    if isinstance(gen.iter, ast.Name) \
                            and gen.iter.id in listmap \
                            and isinstance(gen.target, ast.Name):
                        iters.append((gen.target.id,
                                      listmap[gen.iter.id], [node]))
            for var, types, body in iters:
                self._collect_reads(body, var, types)
        # 4. scalar reads (next()-selected single events)
        for var, types in scalarmap.items():
            self._collect_reads(own_nodes, var, types, walked=True)
        # 5. dispatch branches: k = ev.get("type"); if k == "...":
        dispatch: Dict[str, str] = {}  # dispatch var -> event var
        for node in own_nodes:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "get"
                    and isinstance(node.value.func.value, ast.Name)
                    and node.value.args
                    and _const_str(node.value.args[0]) == "type"):
                dispatch[node.targets[0].id] = node.value.func.value.id
        for node in own_nodes:
            if not isinstance(node, ast.If):
                continue
            for kvar, evar in dispatch.items():
                types = self._dispatch_types(node.test, kvar)
                if types:
                    if collect:
                        for t in sorted(types):
                            self.out.filters.append(ConsumerFilter(
                                event=t, path=self.mod.relpath,
                                line=node.lineno))
                    self._collect_reads(node.body, evar, types)

    @staticmethod
    def _dispatch_types(test, kvar: str) -> Set[str]:
        types: Set[str] = set()
        nodes = test.values if isinstance(test, ast.BoolOp) else [test]
        for t in nodes:
            if not (isinstance(t, ast.Compare)
                    and isinstance(t.left, ast.Name) and t.left.id == kvar
                    and len(t.ops) == 1):
                continue
            comp = t.comparators[0]
            if isinstance(t.ops[0], ast.Eq):
                s = _const_str(comp)
                if s is not None:
                    types.add(s)
            elif isinstance(t.ops[0], ast.In) and isinstance(
                    comp, (ast.Tuple, ast.List, ast.Set)):
                for el in comp.elts:
                    s = _const_str(el)
                    if s is not None:
                        types.add(s)
        return types

    def _collect_reads(self, body, var: str, types: Set[str],
                       walked: bool = False) -> None:
        nodes = body if walked else [
            n for stmt in body for n in ast.walk(stmt)]
        for n in nodes:
            fld = _get_field(n, var)
            if fld is None or fld in ("type", "ts"):
                continue
            for t in sorted(types):
                self.out.reads.append(ConsumerRead(
                    event=t, field=fld, path=self.mod.relpath,
                    line=n.lineno))

    def _scope_nodes(self, scope) -> List[ast.AST]:
        """All nodes of ``scope`` excluding nested function bodies (the
        nested defs are their own scopes; closures over dynamic field
        names read nothing the AST can attribute)."""
        out: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            out.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not scope:
                continue
            stack.extend(ast.iter_child_nodes(n))
        return out

    # -- bench "metric" literals --------------------------------------

    def _bench_metric(self, node: ast.Dict) -> None:
        for k, v in zip(node.keys, node.values):
            if k is None or _const_str(k) != "metric":
                continue
            nm = _static_name(v)
            if nm is None or not nm[0]:
                continue
            self.out.bench_metrics.append(BenchMetric(
                name=nm[0], dynamic=nm[1],
                path=self.mod.relpath, line=node.lineno))

    # -- driver --------------------------------------------------------

    def run(self) -> None:
        self._collect_imports()
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Call):
                self._emit_site(node)
                self._metric_site(node)
            elif isinstance(node, ast.Dict):
                self._dict_emit_site(node)
                if self.bench_mode:
                    self._bench_metric(node)
        # two passes: first threads filtered vars into helper params,
        # second collects reads with the seeded parameter types.  Only
        # the obs consumer modules fold journal events -- a
        # ``.get("type") == ...`` filter anywhere else (column metadata,
        # fault specs) is not a telemetry read.
        if self.consumer_mode:
            self._consumer_pass(collect=False)
            self._consumer_pass(collect=True)


# ------------------------------------------------- repo-level surfaces


def _extract_figures(out: Extraction) -> None:
    """Figure keys/prefixes ``journal_figures`` (obs/slo.py) can fold."""
    slo = PKG_ROOT / "obs" / "slo.py"
    if not slo.exists():
        return
    mod = parse_module(slo)
    fn = next((n for n in mod.tree.body
               if isinstance(n, ast.FunctionDef)
               and n.name == "journal_figures"), None)
    if fn is None:
        return
    seen: Set[Tuple[str, bool]] = set()
    fstring_parts = {id(c) for n in ast.walk(fn)
                     if isinstance(n, ast.JoinedStr)
                     for c in ast.walk(n) if isinstance(c, ast.Constant)}
    for node in ast.walk(fn):
        key = prefix = None
        s = None if id(node) in fstring_parts else _const_str(node)
        if s is not None and "/" in s and _FIGURE_KEY_RE.fullmatch(s):
            key, prefix = s, False
        elif isinstance(node, ast.JoinedStr):
            nm = _static_name(node)
            if nm and nm[1] and "/" in nm[0] \
                    and _FIGURE_KEY_RE.fullmatch(nm[0]):
                key, prefix = nm[0], True
        if key is not None and (key, prefix) not in seen:
            seen.add((key, prefix))
            out.figures.append(FigureKey(key=key, prefix=prefix))


def _extract_fault_kinds(out: Extraction) -> None:
    faults = PKG_ROOT / "testing" / "faults.py"
    if not faults.exists():
        return
    mod = parse_module(faults)
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "VALID_KINDS"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            kinds = tuple(_const_str(el) for el in node.value.elts)
            if all(k is not None for k in kinds):
                out.fault_kinds = kinds
                return


def _scan_fault_refs(out: Extraction, files: Sequence[Path]) -> None:
    for path in files:
        try:
            text = path.read_text()
        except OSError:
            continue
        try:
            rel = path.resolve().relative_to(REPO_ROOT).as_posix()
        except ValueError:
            rel = path.as_posix()
        out.lines.setdefault(rel, text.splitlines())
        for lineno, line in enumerate(text.splitlines(), 1):
            for m in _FAULT_REF_RE.finditer(line):
                kind, argstr = m.group(1), m.group(2)
                keys = {p.partition("=")[0] for p in argstr.split(",")}
                if keys & FAULT_ARG_KEYS:
                    out.fault_refs.append(FaultRef(
                        kind=kind, spec=m.group(0), path=rel, line=lineno))


def _default_fault_scan_files() -> List[Path]:
    files: List[Path] = []
    for sub, pattern in (("tests", "*.py"), ("scripts", "*.py"),
                         ("docs", "*.md")):
        root = REPO_ROOT / sub
        if root.is_dir():
            files.extend(sorted(
                p for p in root.rglob(pattern)
                if "lint_fixtures" not in p.parts
                and "__pycache__" not in p.parts))
    files.extend(sorted(REPO_ROOT.glob("*.md")))
    return files


def extract_repo(paths: Optional[Sequence] = None) -> Extraction:
    """Extract every telemetry surface.

    ``paths=None`` is the repo-wide default: the package plus
    ``bench.py`` and ``scripts/`` for emit/metric/consumer sites, and
    tests/docs/scripts for fault-spec references.  Explicit ``paths``
    (fixture mode) scope the site and fault-ref scans to those files;
    the figure, bench-metric, and fault-kind catalogues always come
    from their canonical producers (``obs/slo.py``, ``bench.py``,
    ``testing/faults.py``) so the rules keep a full reference even on a
    scoped run.
    """
    out = Extraction()
    if paths is None:
        py_files = iter_py_files()
        bench = REPO_ROOT / "bench.py"
        extra = ([bench] if bench.exists() else []) + sorted(
            (REPO_ROOT / "scripts").glob("*.py")
            if (REPO_ROOT / "scripts").is_dir() else [])
        fault_files = _default_fault_scan_files()
    else:
        py_files = iter_py_files(paths)
        extra = []
        fault_files = list(py_files)
    bench_paths = {str(REPO_ROOT / "bench.py")} | {
        str(p) for p in (REPO_ROOT / "scripts").glob("*.py")
        if (REPO_ROOT / "scripts").is_dir()}
    consumer_paths = {
        str(PKG_ROOT / "obs" / name)
        for name in ("report.py", "slo.py", "watch.py")}
    for path in list(py_files) + extra:
        mod = parse_module(path)
        out.lines[mod.relpath] = mod.lines
        _ModuleExtractor(mod, out,
                         bench_mode=str(path) in bench_paths
                         or paths is not None,
                         consumer_mode=str(path) in consumer_paths
                         or paths is not None).run()
    _extract_figures(out)
    _extract_fault_kinds(out)
    _scan_fault_refs(out, fault_files)
    out.emits.sort(key=lambda s: (s.path, s.line, s.event))
    out.metrics.sort(key=lambda s: (s.path, s.line, s.name))
    out.reads = sorted(set(out.reads),
                       key=lambda r: (r.path, r.line, r.event, r.field))
    out.filters = sorted(set(out.filters),
                         key=lambda f: (f.path, f.line, f.event))
    return out
