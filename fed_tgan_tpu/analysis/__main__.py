"""``python -m fed_tgan_tpu.analysis`` -- the analysis-family CLI.

Default mode is the static lint (rules J01-J06 + the locklint
concurrency rules L01-L04, no JAX import).
``--telemetry`` switches to obslint (telemetry contracts O01-O05): the
pure-AST extraction of every journal emit site, metric get-or-create
site, obs consumer read, budget selector, and fault-spec reference is
cross-checked against the registry ``fed_tgan_tpu/obs/schema.json``
(``--schema-update`` regenerates/merges the registry from the tree).
``--contracts`` switches to the IR program contracts: every jitted
entrypoint is AOT-lowered on a simulated 8-device CPU mesh and its
fingerprint diffed against the checked-in ``analysis/contracts/*.json``
(``--contracts-update`` re-records them; ``--explain`` names the op
delta and candidate source sites).
``--all`` runs every prong (jaxlint+locklint, obslint, hlolint
contracts) and prints one summary table with an aggregated exit code.

Exit codes: 0 clean (or all findings baselined / contracts honored),
1 new findings / contract regression, 2 usage, parse, or lowering error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from fed_tgan_tpu.analysis.lint import (
    DEFAULT_BASELINE_PATH,
    LintError,
    apply_baseline,
    load_baseline,
    run_lint,
    save_baseline,
)
from fed_tgan_tpu.analysis.rules import ALL_RULES, RULES_BY_ID


def expand_rule_ids(spec: str) -> list:
    """'J01,L02' -> ['J01', 'L02']; 'L01-L04' expands the numeric range
    within one prefix letter.  Unknown shapes raise KeyError (the same
    path as an unknown id, so the CLI reports it as usage: exit 2)."""
    import re as _re

    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        m = _re.fullmatch(r"([A-Z]+)(\d+)-([A-Z]+)?(\d+)", tok)
        if m:
            prefix, lo, prefix2, hi = m.groups()
            if prefix2 is not None and prefix2 != prefix:
                raise KeyError(tok)
            width = len(m.group(2))
            out.extend(f"{prefix}{n:0{width}d}"
                       for n in range(int(lo), int(hi) + 1))
        else:
            out.append(tok)
    return out


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m fed_tgan_tpu.analysis",
        description="JAX-aware lint (J01-J06 + locklint L01-L04) "
                    "and lowered-HLO program "
                    "contracts (--contracts) over fed_tgan_tpu",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE_PATH,
                    help="baseline JSON of accepted findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline to the current finding set")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to run, ranges allowed "
                         "(e.g. 'L01-L04' or 'J01,J03,L02'; default: all)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--contracts", action="store_true",
                    help="check the lowered-HLO program contracts instead "
                         "of linting (AOT-lowers every jitted entrypoint "
                         "on a simulated 8-device CPU mesh)")
    ap.add_argument("--contracts-update", action="store_true",
                    help="re-record the contract fingerprints from the "
                         "current tree (the explicit ratchet reset)")
    ap.add_argument("--explain", action="store_true",
                    help="with --contracts: name each regression's op "
                         "delta and candidate source sites")
    ap.add_argument("--contracts-dir", type=Path, default=None,
                    help="contract JSON directory (default: the checked-in "
                         "analysis/contracts/)")
    ap.add_argument("--backend", default=None,
                    metavar="{cpu,tpu,gpu,plugin:<name>}",
                    help="with --contracts: check/record against that "
                         "backend's contract directory (cpu = the "
                         "checked-in analysis/contracts/, others get a "
                         "sibling subdirectory, e.g. analysis/contracts/"
                         "tpu/); see runtime/backend.py")
    ap.add_argument("--telemetry", action="store_true",
                    help="check the telemetry contracts (obslint "
                         "O01-O05) against obs/schema.json instead of "
                         "linting")
    ap.add_argument("--schema-update", action="store_true",
                    help="with --telemetry: regenerate/merge the schema "
                         "registry from the current tree (additive; "
                         "curated entries are never deleted)")
    ap.add_argument("--schema", type=Path, default=None,
                    help="with --telemetry: schema registry path "
                         "(default: the checked-in obs/schema.json)")
    ap.add_argument("--budgets", type=Path, default=None,
                    help="with --telemetry: budgets JSON for the O04 "
                         "selector check (default: obs/budgets.json on "
                         "a repo-wide run)")
    ap.add_argument("--all", action="store_true", dest="all_prongs",
                    help="run every analysis prong (jaxlint+locklint, "
                         "obslint, hlolint contracts) with one summary "
                         "table and an aggregated exit code")
    return ap


def _contracts_mode(args) -> int:
    # imported lazily: the contracts prong needs JAX, the lint prong
    # must keep its millisecond no-JAX startup
    from fed_tgan_tpu.analysis.contracts.check import run_contracts

    contracts_dir = args.contracts_dir
    if contracts_dir is None and args.backend is not None:
        from fed_tgan_tpu.runtime.backend import contracts_dir_for

        try:
            contracts_dir = contracts_dir_for(args.backend)
        except ValueError as exc:
            print(f"contracts: {exc}", file=sys.stderr)
            return 2

    return run_contracts(
        update=args.contracts_update,
        explain=args.explain,
        fmt=args.format,
        contracts_dir=contracts_dir,
    )


def _telemetry_mode(args) -> int:
    from fed_tgan_tpu.analysis.telemetry import (
        RULE_IDS,
        extract_repo,
        generate_schema,
        load_schema,
        run_telemetry,
        save_schema,
    )
    from fed_tgan_tpu.analysis.telemetry.schema import DEFAULT_SCHEMA_PATH

    if args.schema_update:
        try:
            ex = extract_repo(args.paths or None)
            path = args.schema or DEFAULT_SCHEMA_PATH
            existing = load_schema(path) if path.exists() else None
            schema, added = generate_schema(ex, existing=existing)
            save_schema(schema, path)
        except LintError as exc:
            print(f"obslint: {exc}", file=sys.stderr)
            return 2
        print(f"obslint: schema updated: {len(added)} addition(s) "
              f"-> {path}")
        for entry in added:
            print(f"  + {entry}")
        return 0

    rules = None
    if args.rules:
        rules = expand_rule_ids(args.rules)
        unknown = sorted(set(rules) - set(RULE_IDS))
        if unknown:
            print(f"obslint: unknown rule(s) {', '.join(unknown)} "
                  f"(have {', '.join(RULE_IDS)})", file=sys.stderr)
            return 2

    try:
        findings, coverage = run_telemetry(
            args.paths or None, schema_path=args.schema,
            budgets_path=args.budgets, rules=rules)
    except LintError as exc:
        print(f"obslint: {exc}", file=sys.stderr)
        return 2

    if args.baseline_update:
        path = save_baseline(findings, args.baseline)
        print(f"obslint: baseline updated: {len(findings)} finding(s) "
              f"-> {path}")
        return 0

    try:
        baseline = set() if args.no_baseline else load_baseline(args.baseline)
    except LintError as exc:
        print(f"obslint: {exc}", file=sys.stderr)
        return 2
    new, old, stale = apply_baseline(findings, baseline)
    stale = {k for k in stale
             if k.split(":")[1].startswith("O")}  # jaxlint keys aren't ours

    cov = (f"schema covers {coverage['emit_sites_covered']}/"
           f"{coverage['emit_sites']} emit site(s), "
           f"{coverage['metric_sites_covered']}/"
           f"{coverage['metric_sites']} metric site(s)")
    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "new": [f.key for f in new],
            "baselined": [f.key for f in old],
            "stale_baseline": sorted(stale),
            "coverage": coverage,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for key in sorted(stale):
            print(f"obslint: stale baseline entry (fixed? run "
                  f"--baseline-update to drop): {key}")
        print(f"obslint: {len(findings)} finding(s): {len(new)} new, "
              f"{len(old)} baselined, {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'}; {cov}")
    return 1 if new else 0


def _all_mode(args) -> int:
    """Every prong, one summary table, aggregated exit code."""
    import argparse as _argparse

    rows = []
    lint_args = _argparse.Namespace(**vars(args))
    lint_args.rules = ""
    rc = _lint_mode(lint_args)
    rows.append(("jaxlint+locklint", rc))
    tel_args = _argparse.Namespace(**vars(args))
    tel_args.rules = ""
    tel_args.schema_update = False
    rc = _telemetry_mode(tel_args)
    rows.append(("obslint", rc))
    con_args = _argparse.Namespace(**vars(args))
    con_args.contracts_update = False
    rc = _contracts_mode(con_args)
    rows.append(("hlolint contracts", rc))

    width = max(len(name) for name, _ in rows)
    print("\nanalysis --all summary:")
    for name, rc in rows:
        status = {0: "ok", 1: "FINDINGS", 2: "ERROR"}.get(rc, f"exit {rc}")
        print(f"  {name:<{width}}  {status}")
    codes = [rc for _, rc in rows]
    return 2 if 2 in codes else (1 if 1 in codes else 0)


def _lint_mode(args) -> int:
    rules = None
    if args.rules:
        try:
            rules = [RULES_BY_ID[r] for r in expand_rule_ids(args.rules)]
        except KeyError as exc:
            print(f"jaxlint: unknown rule {exc} "
                  f"(have {sorted(RULES_BY_ID)})", file=sys.stderr)
            return 2

    try:
        findings = run_lint(args.paths or None, rules=rules)
    except LintError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2

    if args.baseline_update:
        path = save_baseline(findings, args.baseline)
        print(f"jaxlint: baseline updated: {len(findings)} finding(s) "
              f"-> {path}")
        return 0

    try:
        baseline = set() if args.no_baseline else load_baseline(args.baseline)
    except LintError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2
    new, old, stale = apply_baseline(findings, baseline)
    stale = {k for k in stale
             if not k.split(":")[1].startswith("O")}  # obslint keys

    if args.format == "json":
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "new": [f.key for f in new],
            "baselined": [f.key for f in old],
            "stale_baseline": sorted(stale),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for key in sorted(stale):
            print(f"jaxlint: stale baseline entry (fixed? run "
                  f"--baseline-update to drop): {key}")
        print(f"jaxlint: {len(findings)} finding(s): {len(new)} new, "
              f"{len(old)} baselined, {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'} "
              f"[rules: {', '.join(r.rule_id for r in (rules or ALL_RULES))}]")
    return 1 if new else 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.all_prongs:
        return _all_mode(args)
    if args.contracts or args.contracts_update:
        return _contracts_mode(args)
    if args.telemetry or args.schema_update:
        return _telemetry_mode(args)
    return _lint_mode(args)


if __name__ == "__main__":
    raise SystemExit(main())
