"""jaxlint driver: walk files, run the J01-J06 + L01-L04 rules, diff the baseline.

Pure stdlib + AST -- importing this module never imports JAX, so the
lint gate runs in milliseconds with no tracing.  Findings are keyed
``relpath:rule:line``; the checked-in ``baseline.json`` holds accepted
pre-existing findings so the gate starts green and only *new* findings
fail it (ratchet: shrink the baseline as hot paths get fixed, never
grow it silently -- growth requires an explicit ``--baseline-update``).

Inline escape hatch for intentional syncs::

    out.append(np.asarray(chunk))  # jaxlint: disable=J01
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from fed_tgan_tpu.analysis.rules import ALL_RULES

PKG_ROOT = Path(__file__).resolve().parent.parent  # .../fed_tgan_tpu
REPO_ROOT = PKG_ROOT.parent
DEFAULT_BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?:=(?P<ids>[A-Z0-9,\s]+))?")


class LintError(RuntimeError):
    """Unreadable / unparsable input (CLI exit code 2)."""


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str
    hint: str = ""

    @property
    def key(self) -> str:
        return f"{self.path}:{self.rule}:{self.line}"

    def render(self, with_hint: bool = True) -> str:
        text = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if with_hint and self.hint:
            text += f"\n    hint: {self.hint}"
        return text


@dataclass
class ModuleInfo:
    path: str
    relpath: str
    source: str
    lines: List[str]
    tree: ast.Module


def iter_py_files(paths: Optional[Sequence] = None) -> List[Path]:
    roots = [Path(p) for p in paths] if paths else [PKG_ROOT]
    files: List[Path] = []
    for root in roots:
        if root.is_dir():
            files.extend(sorted(
                p for p in root.rglob("*.py")
                if "__pycache__" not in p.parts))
        elif root.suffix == ".py":
            files.append(root)
        else:
            raise LintError(f"not a python file or directory: {root}")
    return files


def _relpath(path: Path) -> str:
    try:
        return path.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.as_posix()


def parse_module(path: Path) -> ModuleInfo:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError) as exc:
        raise LintError(f"{path}: {exc}") from exc
    return ModuleInfo(path=str(path), relpath=_relpath(path),
                      source=source, lines=source.splitlines(), tree=tree)


def _suppressed(mod: ModuleInfo, rule: str, line: int) -> bool:
    for ln in (line, line - 1):
        if 1 <= ln <= len(mod.lines):
            m = _SUPPRESS_RE.search(mod.lines[ln - 1])
            if m:
                ids = m.group("ids")
                if ids is None:
                    return True
                if rule in {s.strip() for s in ids.split(",")}:
                    return True
    return False


def lint_module(mod: ModuleInfo, rules=None) -> List[Finding]:
    out: List[Finding] = []
    for rule in (rules or ALL_RULES):
        for rule_id, line, message, hint in rule.check(mod):
            if not _suppressed(mod, rule_id, line):
                out.append(Finding(rule=rule_id, path=mod.relpath,
                                   line=line, message=message, hint=hint))
    return out


def run_lint(paths: Optional[Sequence] = None, rules=None) -> List[Finding]:
    """Lint ``paths`` (default: the whole ``fed_tgan_tpu`` package)."""
    findings: List[Finding] = []
    seen: Set[str] = set()
    for path in iter_py_files(paths):
        for f in lint_module(parse_module(path), rules=rules):
            if f.key not in seen:
                seen.add(f.key)
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------- baseline

def load_baseline(path: Optional[Path] = None) -> Set[str]:
    path = Path(path) if path else DEFAULT_BASELINE_PATH
    if not path.exists():
        return set()
    try:
        data = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise LintError(f"bad baseline {path}: {exc}") from exc
    return set(data.get("findings", {}))


def save_baseline(findings: Iterable[Finding],
                  path: Optional[Path] = None) -> Path:
    path = Path(path) if path else DEFAULT_BASELINE_PATH
    payload = {
        "version": 1,
        "comment": ("accepted pre-existing jaxlint findings; shrink via "
                    "fixes, grow only via --baseline-update"),
        "findings": {f.key: f.message for f in findings},
    }
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return path


def apply_baseline(findings: Sequence[Finding], baseline: Set[str]
                   ) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """-> (new_findings, baselined_findings, stale_baseline_keys)."""
    new = [f for f in findings if f.key not in baseline]
    old = [f for f in findings if f.key in baseline]
    stale = baseline - {f.key for f in findings}
    return new, old, stale
