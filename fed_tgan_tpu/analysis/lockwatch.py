"""lockwatch -- the runtime prong of locklint: an opt-in deadlock
sanitizer + lock metrics for the threaded serving/observability plane.

Same contract as ``sanitizers.py``: **zero-cost when uninstalled** (no
import-time patching, plain ``threading`` locks untouched), armed
explicitly by tests / ``doctor`` / ``bench`` / ``fleet --lockwatch``.
While installed, every lock created through ``threading.Lock()`` /
``threading.RLock()`` is wrapped; each wrapper records, per thread, the
stack of locks currently held and feeds a **global lock-order graph**
(GoodLock): acquiring B while holding A adds the edge A->B, and a path
B ->* A already in the graph means two threads can interleave the two
orders into a deadlock -- reported *without* needing the unlucky
schedule to actually happen.  Two report kinds:

* ``reentry`` -- a thread blocking-acquires a non-reentrant lock it
  already holds (the PR 9 ``submit`` -> ``_shed`` shape).  This is a
  *certain* deadlock, so it always raises :class:`DeadlockError`
  instead of hanging the process, whatever the ``on_deadlock`` policy.
* ``cycle`` -- the order graph closed a cycle.  Potential deadlock:
  recorded, and raised as well under ``on_deadlock="raise"``.

Locks are identified by *allocation-site name* (``serve.fleet:__init__``
-- stable across instances, so two instances of one class still build
meaningful order edges); :func:`set_name` assigns curated names to the
locks a budget tracks (``fleet_adm``, ``row_pool``).  Per-lock
hold-time and acquire-wait histograms accumulate in-process and export
to ``obs/registry.py`` as labeled Prometheus series
(``fed_tgan_lock_hold_seconds{lock="..."}``) via
:func:`export_to_registry`; :func:`summary` returns the
``lock/<name>/hold_p99_ms`` figures the serving-fleet bench feeds to
the SLO budget gate.

Caveat: ``Condition.wait`` releases its lock through the inner lock's
``_release_save`` (delegated, uncounted), so a waiter's hold-time
includes the waited interval -- fine for the contention signal these
histograms exist for.
"""

from __future__ import annotations

import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

__all__ = [
    "DeadlockError",
    "DeadlockReport",
    "WatchedLock",
    "clear",
    "export_to_registry",
    "install",
    "installed",
    "reports",
    "set_name",
    "summary",
    "uninstall",
    "watch",
    "wrap",
]

# real factories captured at import time: lockwatch's own state and the
# uninstall path must never route through the wrappers
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: stdlib layers to skip when naming a lock by its allocation site --
#: ``queue.Queue()`` allocates its mutex inside ``queue``, but the
#: interesting site is whoever built the queue
_OPAQUE_MODULES = ("threading", "queue", "logging", "asyncio", "selectors",
                   "socketserver", "http", "concurrent",
                   "fed_tgan_tpu.analysis.lockwatch")

#: per-lock sample cap -- enough for exact p99 over a bench window
#: without unbounded growth on million-op runs
_MAX_SAMPLES = 100_000


class DeadlockError(RuntimeError):
    """Raised instead of letting the offending ``acquire`` hang."""


@dataclass
class DeadlockReport:
    kind: str                  # "reentry" | "cycle"
    locks: Tuple[str, ...]     # reentry: (name,); cycle: path, first==last
    thread: str
    detail: str


@dataclass
class _LockStats:
    acquisitions: int = 0
    contentions: int = 0
    holds: List[float] = field(default_factory=list)    # seconds
    waits: List[float] = field(default_factory=list)    # contended waits
    exported_holds: int = 0
    exported_waits: int = 0


class _State:
    def __init__(self) -> None:
        self.lock = _REAL_LOCK()
        self.installed = False
        self.raise_on_cycle = True
        self.edges: Dict[Tuple[str, str], str] = {}    # (a, b) -> detail
        self.reports: List[DeadlockReport] = []
        self.report_keys: Set[FrozenSet[str]] = set()
        self.stats: Dict[str, _LockStats] = {}


_STATE = _State()
_HELD = threading.local()   # .stack: List[Tuple[WatchedLock, float]]


def _thread_name() -> str:
    """Current thread's name WITHOUT ``threading.current_thread()``:
    that helper allocates a ``_DummyThread`` (whose ``Event`` touches a
    watched lock) when called during thread bootstrap, before the
    thread registers itself -- infinite recursion.  A raw ``_active``
    dict read is safe under the GIL and allocation-free."""
    ident = threading.get_ident()
    t = threading._active.get(ident)
    return t.name if t is not None else f"tid-{ident}"


def _held_stack() -> list:
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    return stack


def _site_name() -> str:
    f = sys._getframe(2)
    while f is not None:
        mod = f.f_globals.get("__name__", "")
        if not any(mod == m or mod.startswith(m + ".")
                   for m in _OPAQUE_MODULES):
            short = mod
            if short.startswith("fed_tgan_tpu."):
                short = short[len("fed_tgan_tpu."):]
            return f"{short}:{f.f_code.co_name}"
        f = f.f_back
    return "anon"


class WatchedLock:
    """Duck-typed ``threading.Lock``/``RLock`` stand-in.

    ``acquire``/``release``/``locked`` and the context protocol are
    instrumented; everything else (``_release_save`` / ``_is_owned`` /
    ... as used by ``threading.Condition``) delegates to the wrapped
    lock via ``__getattr__`` -- so a Condition built on a primitive
    watched lock still sees the AttributeError it uses to pick its
    fallback path, and one built on a watched RLock gets the real
    reentrancy internals.
    """

    def __init__(self, inner, name: str, reentrant: bool) -> None:
        self._inner = inner
        self.name = name
        self.reentrant = reentrant

    # ------------------------------------------------------ lock protocol

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        armed = _STATE.installed
        held = _held_stack() if armed else None
        if armed and blocking:
            self._check(held, indefinite=timeout is None or timeout < 0)
        got = self._inner.acquire(False)
        wait = 0.0
        contended = got is False
        if not got:
            if not blocking:
                if armed:
                    self._record_acquire(contended=True, wait=None)
                return False
            t0 = time.perf_counter()
            got = self._inner.acquire(True, timeout)
            wait = time.perf_counter() - t0
        if got and armed:
            held.append((self, time.perf_counter()))
            self._record_acquire(contended=contended,
                                 wait=wait if contended else 0.0)
        return got

    def release(self) -> None:
        if _STATE.installed:
            held = _held_stack()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    hold = time.perf_counter() - held[i][1]
                    del held[i]
                    self._record_release(hold)
                    break
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        # RLock before the `locked()` API: probe via non-blocking acquire
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __getattr__(self, attr):
        return getattr(object.__getattribute__(self, "_inner"), attr)

    def __repr__(self) -> str:
        return f"<WatchedLock {self.name!r} wrapping {self._inner!r}>"

    # ------------------------------------------------------- bookkeeping

    def _record_acquire(self, contended: bool,
                        wait: Optional[float]) -> None:
        with _STATE.lock:
            st = _STATE.stats.setdefault(self.name, _LockStats())
            if wait is not None:
                st.acquisitions += 1
            if contended:
                st.contentions += 1
            if wait and len(st.waits) < _MAX_SAMPLES:
                st.waits.append(wait)

    def _record_release(self, hold: float) -> None:
        with _STATE.lock:
            st = _STATE.stats.setdefault(self.name, _LockStats())
            if len(st.holds) < _MAX_SAMPLES:
                st.holds.append(hold)

    def _check(self, held: list, indefinite: bool) -> None:
        """Reentry + order-graph update before a blocking acquire."""
        me = _thread_name()
        if not self.reentrant and any(w is self for w, _ in held):
            report = DeadlockReport(
                kind="reentry", locks=(self.name,), thread=me,
                detail=(f"thread {me!r} re-acquired non-reentrant lock "
                        f"{self.name!r} it already holds"))
            with _STATE.lock:
                _STATE.reports.append(report)
            if indefinite:
                # proceeding would hang the thread forever: always raise
                raise DeadlockError(report.detail)
            return
        cycle_report = None
        with _STATE.lock:
            for w, _ in held:
                if w.name == self.name:
                    continue
                edge = (w.name, self.name)
                if edge in _STATE.edges:
                    continue
                path = self._find_path(self.name, w.name)
                _STATE.edges[edge] = (f"thread {me!r} acquired "
                                      f"{self.name!r} holding {w.name!r}")
                if path is not None:
                    cycle = (w.name,) + tuple(path)
                    key = frozenset(cycle)
                    if key not in _STATE.report_keys:
                        _STATE.report_keys.add(key)
                        cycle_report = DeadlockReport(
                            kind="cycle", locks=cycle, thread=me,
                            detail=("lock-order cycle "
                                    + " -> ".join(cycle)
                                    + f" (closed by thread {me!r})"))
                        _STATE.reports.append(cycle_report)
            raise_on_cycle = _STATE.raise_on_cycle
        if cycle_report is not None and raise_on_cycle:
            raise DeadlockError(cycle_report.detail)

    @staticmethod
    def _find_path(src: str, dst: str) -> Optional[List[str]]:
        """Path src ->* dst in the order graph (caller holds _STATE.lock);
        adding dst->src then closes a cycle."""
        adj: Dict[str, List[str]] = {}
        for a, b in _STATE.edges:
            adj.setdefault(a, []).append(b)
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


# ------------------------------------------------------------- factories

def _watched_lock():
    return WatchedLock(_REAL_LOCK(), _site_name(), reentrant=False)


def _watched_rlock():
    return WatchedLock(_REAL_RLOCK(), _site_name(), reentrant=True)


def wrap(lock, name: Optional[str] = None,
         reentrant: bool = False) -> WatchedLock:
    """Explicitly wrap an existing lock (for targeted instrumentation
    without installing the global factories)."""
    return WatchedLock(lock, name or _site_name(), reentrant=reentrant)


def set_name(lock, name: str) -> None:
    """Curated stable name for a lock the budgets reference.  No-op for
    plain (unwatched) locks so call sites need no feature gate."""
    if isinstance(lock, WatchedLock):
        lock.name = name


# ---------------------------------------------------------- arm / disarm

def install(on_deadlock: str = "raise") -> None:
    """Patch the ``threading.Lock``/``RLock`` factories.  Locks created
    from here on are watched; pre-existing locks are untouched.

    ``on_deadlock``: ``"raise"`` turns a detected order cycle into an
    immediate :class:`DeadlockError` at the closing acquire;
    ``"record"`` only appends to :func:`reports`.  Certain single-
    thread re-entry deadlocks always raise (the alternative is a hang).
    """
    if on_deadlock not in ("raise", "record"):
        raise ValueError(f"on_deadlock: {on_deadlock!r}")
    with _STATE.lock:
        if _STATE.installed:
            raise RuntimeError("lockwatch already installed")
        _STATE.installed = True
        _STATE.raise_on_cycle = on_deadlock == "raise"
    threading.Lock = _watched_lock
    threading.RLock = _watched_rlock


def uninstall() -> None:
    """Restore the real factories.  Existing wrappers fall back to plain
    delegation (the ``installed`` flag gates all bookkeeping), and the
    collected stats/reports survive until :func:`clear`."""
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    with _STATE.lock:
        _STATE.installed = False


def installed() -> bool:
    return _STATE.installed


def clear() -> None:
    with _STATE.lock:
        _STATE.edges.clear()
        _STATE.reports.clear()
        _STATE.report_keys.clear()
        _STATE.stats.clear()


@contextmanager
def watch(on_deadlock: str = "raise", clear_first: bool = True):
    """``with lockwatch.watch(): ...`` -- arm, run, disarm.  The state
    is cleared on entry (not exit) so callers can inspect reports and
    stats after the block."""
    if clear_first:
        clear()
    install(on_deadlock=on_deadlock)
    try:
        yield sys.modules[__name__]
    finally:
        uninstall()


# -------------------------------------------------------------- queries

def reports(kind: Optional[str] = None) -> List[DeadlockReport]:
    with _STATE.lock:
        out = list(_STATE.reports)
    return [r for r in out if kind is None or r.kind == kind]


def _quantile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    lat = sorted(samples)
    return lat[min(len(lat) - 1, max(0, round(q * (len(lat) - 1))))]


def summary() -> Dict[str, Dict[str, float]]:
    """Per-lock stats: the ``lock/<name>/hold_p99_ms`` figures for the
    bench record / SLO budget gate, plus contention context."""
    with _STATE.lock:
        items = [(name, st.acquisitions, st.contentions,
                  list(st.holds), list(st.waits))
                 for name, st in _STATE.stats.items()]
    out: Dict[str, Dict[str, float]] = {}
    for name, acq, cont, holds, waits in items:
        out[name] = {
            "acquisitions": acq,
            "contentions": cont,
            "hold_p50_ms": round(_quantile(holds, 0.50) * 1e3, 4),
            "hold_p99_ms": round(_quantile(holds, 0.99) * 1e3, 4),
            "hold_max_ms": round(max(holds) * 1e3, 4) if holds else 0.0,
            "wait_p99_ms": round(_quantile(waits, 0.99) * 1e3, 4),
        }
    return out


def export_to_registry(registry=None) -> None:
    """Flush accumulated samples into ``obs.registry`` labeled series
    (``fed_tgan_lock_hold_seconds{lock=...}`` / ``_wait_seconds`` /
    ``_contentions_total``).  Incremental: each call exports only the
    samples collected since the last one, so periodic flushes do not
    double-count."""
    from fed_tgan_tpu.obs.registry import get_registry

    reg = registry if registry is not None else get_registry()
    with _STATE.lock:
        batches = []
        for name, st in _STATE.stats.items():
            batches.append((name,
                            st.holds[st.exported_holds:],
                            st.waits[st.exported_waits:],
                            st.contentions))
            st.exported_holds = len(st.holds)
            st.exported_waits = len(st.waits)
    for name, holds, waits, contentions in batches:
        labels = {"lock": name}
        hold_h = reg.histogram("fed_tgan_lock_hold_seconds",
                               "lock hold time (lockwatch)", labels=labels)
        for v in holds:
            hold_h.observe(v)
        wait_h = reg.histogram("fed_tgan_lock_wait_seconds",
                               "contended acquire wait (lockwatch)",
                               labels=labels)
        for v in waits:
            wait_h.observe(v)
        g = reg.gauge("fed_tgan_lock_contentions_total",
                      "contended acquires seen by lockwatch",
                      labels=labels)
        g.set(contentions)
