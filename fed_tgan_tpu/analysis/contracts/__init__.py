"""IR-level program contracts (hlolint).

jaxlint (J01-J06) sees Python source; this package sees what the
compiler actually emitted.  Every jitted entrypoint -- the fused
federated epoch per trainer variant, the shard_map robust-aggregation
programs, the serve bucket programs -- is AOT-lowered on a simulated
8-device CPU mesh (no accelerator needed) and its StableHLO text is
walked into a structured *fingerprint*: per-collective op counts and
payload bytes, host<->device transfer surface, dtype census, donation
aliasing.  Fingerprints are checked in as ``*.json`` next to this file
and enforced as a two-sided ratchet: a regression (extra collective,
more transfer bytes, an f64 upcast) fails CI; an improvement passes
with a stale-contract warning until ``--contracts-update`` re-records
it.

Run ``python -m fed_tgan_tpu.analysis --contracts``.

Submodules:

* :mod:`.ir`      -- StableHLO text -> :class:`~.ir.Fingerprint`
  (pure stdlib; no JAX import).
* :mod:`.harness` -- hermetic lowering of every entrypoint family over
  synthetic specs/data (JAX imported lazily, CPU-only).
* :mod:`.check`   -- contract persistence, two-sided diff, ``--explain``
  rendering with candidate source sites, CLI exit-code policy.
"""

from fed_tgan_tpu.analysis.contracts.ir import (  # noqa: F401
    Fingerprint,
    fingerprint_text,
)

__all__ = ["Fingerprint", "fingerprint_text"]
