"""Hermetic AOT-lowering of every contracted jitted entrypoint.

Each builder constructs its program exactly the way the production
caller does -- ``make_federated_epoch`` with stacked client tables,
``robust_aggregate`` inside the same shard_map shape the fused epoch
uses, the serve engine's ``build_bucket_program`` -- but over a fully
synthetic table spec (``SegmentSpec.from_output_info``) and
deterministic synthetic data, so lowering needs no dataset, no fitted
transformer, and no accelerator: an 8-virtual-device CPU mesh
(``provision_virtual_cpu(8)``) is enough.  ``.lower()`` traces but never
executes, so the whole sweep is seconds of CPU.

Coverage note: ``train/multihost.py`` reuses ``make_federated_epoch``
for its per-host program (only the mesh spans hosts), so the fused-epoch
contracts cover the multihost program shape too;
``parallel/multihost.py``'s participant mesh needs a multi-process world
and cannot be lowered in-process.

JAX is imported lazily so the lint prong of ``python -m
fed_tgan_tpu.analysis`` keeps its no-JAX startup.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from fed_tgan_tpu.analysis.contracts.ir import Fingerprint, fingerprint_text
from fed_tgan_tpu.serve.naming import fleet_bucket_name, serve_bucket_name

__all__ = [
    "ENTRYPOINT_FAMILIES",
    "HarnessError",
    "N_DEVICES",
    "PROGRAM_REQUIREMENTS",
    "lower_fingerprints",
    "require_mesh",
]

N_DEVICES = 8  #: simulated mesh width; matches the tests/CI recipe

#: the synthetic table every builder shares: two continuous columns
#: (tanh scalar + mode one-hot is modeled as tanh segments here) and two
#: discrete ones -- wide enough to exercise every segment op, small
#: enough that lowering is instant.
_OUTPUT_INFO = ((1, "tanh"), (3, "softmax"), (1, "tanh"), (4, "softmax"))
_ROWS = 16  #: per-client rows -> 2 local steps at batch_size 8


class HarnessError(RuntimeError):
    """Lowering unavailable on this host (CLI exit code 2)."""


def require_mesh(n: int = N_DEVICES) -> None:
    """Ensure >= ``n`` CPU devices exist, provisioning a virtual CPU
    platform when no backend is initialized yet.  Raises
    :class:`HarnessError` when the process is already bound to an
    unsuitable backend (e.g. a 1-device accelerator)."""
    try:
        import jax

        from fed_tgan_tpu.parallel.mesh import (
            backend_initialized,
            provision_virtual_cpu,
        )
    except Exception as exc:  # pragma: no cover - broken install
        raise HarnessError(f"jax unavailable: {exc!r}") from exc
    if backend_initialized():
        devices = jax.devices()
        if len(devices) < n:
            raise HarnessError(
                f"need {n} devices to lower the mesh programs, have "
                f"{len(devices)} ({devices[0].platform}); run in a fresh "
                f"process with XLA_FLAGS=--xla_force_host_platform_"
                f"device_count={n} JAX_PLATFORMS=cpu"
            )
        return
    try:
        provision_virtual_cpu(n)
    except Exception as exc:
        raise HarnessError(f"could not provision {n} virtual CPU "
                           f"devices: {exc}") from exc


# ------------------------------------------------------------ toy inputs

def _toy_spec():
    from fed_tgan_tpu.ops.segments import SegmentSpec

    return SegmentSpec.from_output_info(_OUTPUT_INFO)


def _toy_cfg(**overrides):
    from fed_tgan_tpu.train.steps import TrainConfig

    kw = dict(embedding_dim=4, gen_dims=(8,), dis_dims=(8,),
              batch_size=8, pac=2)
    kw.update(overrides)
    return TrainConfig(**kw)


def _toy_matrix(spec, seed: int, rows: int = _ROWS) -> np.ndarray:
    """A deterministic transformed matrix: uniform tanh scalars, one-hot
    discrete blocks covering every option (values only seed the sampler
    tables -- the program shape never depends on them)."""
    rng = np.random.RandomState(seed)
    mat = np.zeros((rows, spec.dim), dtype=np.float32)
    tanh_dims = np.flatnonzero(spec.is_tanh_dim)
    mat[:, tanh_dims] = rng.uniform(-1.0, 1.0, (rows, len(tanh_dims)))
    for c in range(spec.n_discrete):
        lo = spec.cond_offsets[c]
        dims = spec.discrete_dims[lo:lo + spec.cond_sizes[c]]
        # round-robin base guarantees every option occurs in every shard
        choice = (np.arange(rows) + rng.randint(0, len(dims))) % len(dims)
        mat[np.arange(rows), dims[choice]] = 1.0
    return mat


def _client_stacks(spec, cfg, n_clients: int = N_DEVICES):
    from fed_tgan_tpu.train.federated import _stack_samplers
    from fed_tgan_tpu.train.sampler import CondSampler, RowSampler

    mats = [_toy_matrix(spec, seed=i) for i in range(n_clients)]
    cond = _stack_samplers([CondSampler.from_data(m, spec) for m in mats])
    rows = _stack_samplers([RowSampler.from_data(m, spec) for m in mats])
    data = np.stack(mats)
    steps = np.full((n_clients,), _ROWS // cfg.batch_size, dtype=np.int32)
    weights = np.full((n_clients,), 1.0 / n_clients, dtype=np.float32)
    return data, cond, rows, steps, weights


def _stacked_models(spec, cfg, n_clients: int = N_DEVICES):
    import jax

    from fed_tgan_tpu.train.steps import init_models

    one = init_models(jax.random.key(0), spec, cfg)
    return one, jax.tree.map(
        lambda x: np.broadcast_to(
            np.asarray(x)[None], (n_clients,) + np.shape(x)).copy(),
        one,
    )


# ------------------------------------------- entrypoint family builders

#: fused-epoch trainer variants: cfg deltas relative to _toy_cfg().
#: "weighted" disables the gate so the legacy single-psum program
#: (bit-identical to pre-robust builds) stays under contract alongside
#: the gated/median robust programs and the EMA signature variant.
_EPOCH_VARIANTS = {
    "weighted": dict(update_gate=False),
    "gated": dict(),
    "median": dict(aggregator="median"),
    "ema": dict(update_gate=False, ema_decay=0.999),
    # mixed-precision twins of the two production paths: bf16 compute +
    # bf16 aggregation payloads, f32 islands intact (PROGRAM_REQUIREMENTS
    # below turns those properties into contract REQUIREMENTS)
    "weighted@bf16": dict(update_gate=False, precision="bf16"),
    "gated@bf16": dict(precision="bf16"),
}


def _lower_epoch(variant: str):
    import jax

    from fed_tgan_tpu.parallel.mesh import client_mesh
    from fed_tgan_tpu.train.federated import make_federated_epoch

    require_mesh()
    spec = _toy_spec()
    cfg = _toy_cfg(**_EPOCH_VARIANTS[variant])
    mesh = client_mesh(N_DEVICES)
    data, cond, rows, steps, weights = _client_stacks(spec, cfg)
    one, models = _stacked_models(spec, cfg)
    # rounds=2 exercises the round scan; collectives inside lax.scan
    # appear once in the IR regardless of length
    fn = make_federated_epoch(spec, cfg, max_steps=int(steps.max()),
                              mesh=mesh, k=1, rounds=2)
    args = [models, data, cond, rows, steps, weights, jax.random.key(0)]
    if cfg.ema_decay > 0.0:
        args.append(jax.tree.map(lambda x: np.zeros_like(np.asarray(x)),
                                 (one.params_g, one.state_g)))
    return fn.lower(*args)


def _lower_fused_rounds(k_rounds: int, precision: str = "f32"):
    """The scan-over-rounds training program at fusion width K — the
    exact builder the trainer's ``--rounds-per-program K`` path compiles
    (``make_federated_epoch`` with ``rounds=K``), on the production
    default (gated) config or its bf16 twin.

    Collectives inside the round scan appear ONCE in the lowered IR
    regardless of K, so a correctly fused program's collective totals are
    byte-identical to ``fused_rounds[1]`` while its LOGICAL traffic
    scales exactly K×.  The ``collective_bytes_scale`` require block
    below pins that equality: IR totals growing toward K× the baseline
    means the scan unrolled into per-round collectives; any other delta
    means the per-round aggregation payload re-widened."""
    import jax

    from fed_tgan_tpu.parallel.mesh import client_mesh
    from fed_tgan_tpu.train.federated import make_federated_epoch

    require_mesh()
    spec = _toy_spec()
    cfg = _toy_cfg(**({} if precision == "f32"
                      else {"precision": "bf16"}))
    mesh = client_mesh(N_DEVICES)
    data, cond, rows, steps, weights = _client_stacks(spec, cfg)
    _one, models = _stacked_models(spec, cfg)
    fn = make_federated_epoch(spec, cfg, max_steps=int(steps.max()),
                              mesh=mesh, k=1, rounds=k_rounds)
    return fn.lower(models, data, cond, rows, steps, weights,
                    jax.random.key(0))


#: fixed cohort size for the cohort_rounds family: every population is
#: sampled down to the SAME per-round cohort, so the lowered programs'
#: collective totals must be byte-identical across N (the O(C) + O(model)
#: round-payload invariant).
_COHORT_C = 8


def _lower_cohort(n_clients: int):
    """Cohort-sampled partial participation at population ``n_clients``
    (packed ``k = n_clients / N_DEVICES`` per device), cohort fixed at
    ``_COHORT_C`` — the exact trainer program ``--cohort C`` compiles.

    Per-round collectives under cohort sampling are one scalar psum (the
    cohort weight renormalization), the model-sized aggregation psum, and
    the gate's cohort-sized scalar all_gathers — all independent of the
    resident population N.  The ``collective_bytes_independent`` require
    block below pins that: collective totals growing with N means
    something collected over the population axis instead of the cohort
    slice."""
    import jax

    from fed_tgan_tpu.parallel.mesh import client_mesh
    from fed_tgan_tpu.train.federated import make_federated_epoch

    require_mesh()
    spec = _toy_spec()
    cfg = _toy_cfg(cohort=_COHORT_C)
    mesh = client_mesh(N_DEVICES)
    k = n_clients // N_DEVICES
    data, cond, rows, steps, weights = _client_stacks(spec, cfg, n_clients)
    _one, models = _stacked_models(spec, cfg, n_clients)
    fn = make_federated_epoch(spec, cfg, max_steps=int(steps.max()),
                              mesh=mesh, k=k, rounds=2)
    return fn.lower(models, data, cond, rows, steps, weights,
                    jax.random.key(0))


def _agg_trees():
    """A two-leaf pytree with the (n_clients, k, ...) layout
    robust_aggregate sees inside the fused epoch."""
    prev = {"w": np.zeros((N_DEVICES, 1, 4, 3), np.float32),
            "b": np.zeros((N_DEVICES, 1, 4), np.float32)}
    new = {"w": np.ones((N_DEVICES, 1, 4, 3), np.float32),
           "b": np.ones((N_DEVICES, 1, 4), np.float32)}
    weights = np.full((N_DEVICES,), 1.0 / N_DEVICES, np.float32)
    steps = np.ones((N_DEVICES,), np.int32)
    return prev, new, weights, steps


def _lower_robust(aggregator: str, payload_bf16: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from fed_tgan_tpu.parallel.fedavg import robust_aggregate
    from fed_tgan_tpu.parallel.mesh import (
        CLIENTS_AXIS,
        client_mesh,
        shard_map,
    )

    require_mesh()
    mesh = client_mesh(N_DEVICES)
    payload_dtype = jnp.bfloat16 if payload_bf16 else None

    def prog(prev, new, w, s):
        return robust_aggregate(prev, new, w, s, k=1,
                                aggregator=aggregator,
                                payload_dtype=payload_dtype)

    fn = shard_map(
        prog, mesh=mesh,
        in_specs=(P(CLIENTS_AXIS),) * 4,
        out_specs=(P(), P(CLIENTS_AXIS)),
        check_vma=False,
    )
    return jax.jit(fn).lower(*_agg_trees())


def _lower_weighted_psum():
    """The legacy aggregation: one psum of weight-scaled leaves."""
    import jax
    from jax.sharding import PartitionSpec as P

    from fed_tgan_tpu.parallel.fedavg import weighted_average
    from fed_tgan_tpu.parallel.mesh import (
        CLIENTS_AXIS,
        client_mesh,
        shard_map,
    )

    require_mesh()
    mesh = client_mesh(N_DEVICES)
    fn = shard_map(
        lambda t, w: weighted_average(t, w),
        mesh=mesh,
        in_specs=(P(CLIENTS_AXIS), P(CLIENTS_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    prev, _new, weights, _steps = _agg_trees()
    return jax.jit(fn).lower(prev, weights)


def _lower_weighted_delta():
    """The bf16 aggregation: f32-accumulated weighted deltas whose psum
    payload crosses the wire at bf16 width."""
    import jax
    from jax.sharding import PartitionSpec as P

    from fed_tgan_tpu.parallel.fedavg import weighted_delta_average
    from fed_tgan_tpu.parallel.mesh import (
        CLIENTS_AXIS,
        client_mesh,
        shard_map,
    )

    require_mesh()
    mesh = client_mesh(N_DEVICES)
    fn = shard_map(
        lambda p, n, w: weighted_delta_average(p, n, w),
        mesh=mesh,
        in_specs=(P(CLIENTS_AXIS),) * 3,
        out_specs=P(),
        check_vma=False,
    )
    prev, new, weights, _steps = _agg_trees()
    return jax.jit(fn).lower(prev, new, weights)


#: synthetic decode layout matching ``_OUTPUT_INFO``'s encoded width
#: (tanh+3 modes, tanh+4 modes = 9 = spec.dim): two continuous columns
_TOY_LAYOUT = (("cont", 3), ("cont", 4))


def _toy_tables():
    return tuple(
        (np.linspace(-1.0, 1.0, size, dtype=np.float32),
         np.linspace(0.5, 1.5, size, dtype=np.float32))
        for _, size in _TOY_LAYOUT
    )


def _serve_args(spec, cfg, n_steps: int):
    import jax

    from fed_tgan_tpu.models.ctgan import init_generator
    from fed_tgan_tpu.train.sampler import CondSampler

    params_g, state_g = init_generator(
        jax.random.key(1), cfg.embedding_dim + spec.n_opt, cfg.gen_dims,
        spec.dim)
    cond = CondSampler.from_data(_toy_matrix(spec, seed=0), spec)
    out = np.zeros((n_steps * cfg.batch_size, len(_TOY_LAYOUT)), np.float32)
    return (params_g, state_g, cond, jax.random.key(0), np.int32(0),
            np.int32(0), _toy_tables(), out)


def _lower_serve(n_steps: int, conditional: bool, precision: str = "f32"):
    import jax

    from fed_tgan_tpu.serve.engine import build_bucket_program

    require_mesh()
    spec = _toy_spec()
    cfg = _toy_cfg(precision=precision)
    run = build_bucket_program(spec, cfg, _TOY_LAYOUT, n_steps, conditional)
    # donate_argnums=7 exactly as the engine jits it: the donated output
    # scratch must lower as an output alias (donation_required below)
    return jax.jit(run, donate_argnums=7).lower(
        *_serve_args(spec, cfg, n_steps))


def _lower_serve_lanes(n_steps: int, conditional: bool, lanes: int = 2,
                       precision: str = "f32"):
    """The fleet's cross-tenant lane program: ``lanes`` tenants' stacked
    params/tables through one vmapped bucket dispatch, donated lane-shaped
    scratch — lowered exactly as ``FleetService._lane_program`` builds it."""
    import jax
    import jax.numpy as jnp

    from fed_tgan_tpu.serve.engine import build_bucket_program

    require_mesh()
    spec = _toy_spec()
    cfg = _toy_cfg(precision=precision)
    run = build_bucket_program(spec, cfg, _TOY_LAYOUT, n_steps, conditional)

    def lane_run(params_g, state_g, cond, key, start, pos, tables, out):
        return jax.vmap(run)(params_g, state_g, cond, key, start, pos,
                             tables, out)

    args = _serve_args(spec, cfg, n_steps)
    stack = lambda tree: jax.tree.map(  # noqa: E731
        lambda x: jnp.stack([x] * lanes), tree)
    lane_args = (stack(args[0]), stack(args[1]), stack(args[2]),
                 jnp.stack([args[3]] * lanes),
                 np.zeros(lanes, np.int32), np.zeros(lanes, np.int32),
                 stack(args[6]),
                 np.zeros((lanes,) + args[7].shape, np.float32))
    return jax.jit(lane_run, donate_argnums=7).lower(*lane_args)


def _lower_ingest_fit(batch: int, rows: int):
    """The cohort-batched BGM fit exactly as ``_fit_flat`` dispatches it:
    the process-wide jitted vmap-over-columns program at production
    hyperparameters (N_CLUSTERS=10, 100 sweeps), on one pow2 shape bucket
    ``(batch, rows)`` where batch spans clients x columns.  Shapes here
    are two buckets a real onboarding run actually hits (small cohort and
    packed chunk)."""
    import jax.numpy as jnp

    from fed_tgan_tpu.features.bgm_jax import _jitted_fit

    require_mesh()
    fit = _jitted_fit(10, 100, 1e-6, 0.001)
    xs = jnp.zeros((batch, rows), jnp.float32)
    mask = jnp.ones((batch, rows), jnp.float32)
    return fit.lower(xs, mask)


def _lower_ingest_wd(n_clients: int):
    """The similarity-sketch W1 program: per-client GMM CDFs vs the pooled
    mixture on a shared (C, G) grid, one device program over the whole
    population.  Lowered at two population sizes; the
    ``collective_bytes_independent`` requirement below pins that the
    program stays collective-free (single-device data parallel over N) as
    the population grows."""
    import jax.numpy as jnp

    from fed_tgan_tpu.federation.sketch import GRID_POINTS, _wd_fn

    require_mesh()
    c, k = 2, 10
    means = jnp.zeros((n_clients, c, k), jnp.float32)
    stds = jnp.ones((n_clients, c, k), jnp.float32)
    weights = jnp.full((n_clients, c, k), 1.0 / k, jnp.float32)
    omega = jnp.full((n_clients,), 1.0 / n_clients, jnp.float32)
    grid = jnp.zeros((c, GRID_POINTS), jnp.float32)
    return _wd_fn().lower(means, stds, weights, omega, grid)


#: family -> {program name -> zero-arg builder returning a Lowered}.
#: Contract JSON files are named after the family keys.
ENTRYPOINT_FAMILIES: Dict[str, Dict[str, Callable]] = {
    "train_federated": {
        f"fused_epoch[{v}]": (lambda v=v: _lower_epoch(v))
        for v in _EPOCH_VARIANTS
    },
    "fused_rounds": {
        **{f"fused_rounds[{k}]": (lambda k=k: _lower_fused_rounds(k))
           for k in (1, 2, 4)},
        **{f"fused_rounds[{k}@bf16]":
           (lambda k=k: _lower_fused_rounds(k, "bf16"))
           for k in (1, 2, 4)},
    },
    "cohort_rounds": {
        f"cohort_rounds[n{n}]": (lambda n=n: _lower_cohort(n))
        for n in (16, 32, 64)
    },
    "parallel_fedavg": {
        "fedavg[weighted_psum]": _lower_weighted_psum,
        "fedavg[weighted_delta_bf16]": _lower_weighted_delta,
        **{f"robust_agg[{a}]": (lambda a=a: _lower_robust(a))
           for a in ("weighted", "clipped", "trimmed", "median")},
        **{f"robust_agg[{a}@bf16]":
           (lambda a=a: _lower_robust(a, payload_bf16=True))
           for a in ("weighted", "clipped", "trimmed", "median")},
    },
    "ingest": {
        **{f"ingest_fit[b{b}xr{r}]": (lambda b=b, r=r: _lower_ingest_fit(b, r))
           for b, r in ((8, 128), (64, 128))},
        **{f"ingest_wd[n{n}]": (lambda n=n: _lower_ingest_wd(n))
           for n in (8, 64)},
    },
    "serve_engine": {
        **{serve_bucket_name(n, c): (lambda n=n, c=c: _lower_serve(n, c))
           for n in (1, 4) for c in (False, True)},
        **{serve_bucket_name(n, c, "bf16"):
           (lambda n=n, c=c: _lower_serve(n, c, "bf16"))
           for n in (1, 4) for c in (False, True)},
        **{fleet_bucket_name(n, c, lanes=2):
           (lambda n=n, c=c: _lower_serve_lanes(n, c, lanes=2))
           for n in (1, 4) for c in (False, True)},
    },
}


#: program -> REQUIRED properties, attached to the contract JSON by
#: ``save_contracts`` (so ``--contracts-update`` regenerates them) and
#: re-evaluated against the CURRENT fingerprints on every contract run:
#:
#: * ``dtypes_present``: the program's census must contain these dtypes —
#:   for bf16 programs, "bf16" proves the compute cast survived lowering
#:   and "f32" proves the pinned islands (gp-norm, loss accumulation,
#:   BN statistics, master params / Adam moments held by the caller in
#:   f32) were not swept into bf16;
#: * ``max_collective_bytes_ratio``: total collective payload bytes must
#:   be <= ratio * the named f32 twin program's total (same family,
#:   same run) — the "~2x lower aggregation bytes" acceptance criterion.
#:   Ratios carry headroom over the measured toy-program values: pure
#:   parameter-payload programs land near 0.5, gated/robust ones higher
#:   because the Byzantine gate's f32 scalar all_gathers (deliberately
#:   NOT quantized) are a bigger share of the tiny toy payload;
#: * ``collective_bytes_scale {vs, rounds}``: the program's IR collective
#:   bytes must EQUAL the named single-round baseline's — the scan-over-
#:   rounds invariant (collectives inside ``lax.scan`` lower once, so
#:   logical traffic is exactly ``rounds`` × the baseline iff the IR
#:   totals match; growth = scan unrolled, other deltas = per-round
#:   payload re-widened);
#: * ``collective_bytes_independent {vs}``: the program's IR collective
#:   bytes must EQUAL the named smallest-population sibling's — the
#:   cohort-federation invariant (round collective payload is O(cohort)
#:   + O(model), independent of the resident client population N;
#:   growth with N = something collected over the population axis).
PROGRAM_REQUIREMENTS: Dict[str, Dict[str, dict]] = {
    "train_federated": {
        "fused_epoch[weighted@bf16]": {
            "dtypes_present": ["bf16", "f32"],
            "max_collective_bytes_ratio": {
                "vs": "fused_epoch[weighted]", "ratio": 0.6},
        },
        "fused_epoch[gated@bf16]": {
            "dtypes_present": ["bf16", "f32"],
            "max_collective_bytes_ratio": {
                "vs": "fused_epoch[gated]", "ratio": 0.65},
        },
    },
    "fused_rounds": {
        **{f"fused_rounds[{k}]": {
            "collective_bytes_scale": {"vs": "fused_rounds[1]",
                                       "rounds": k},
           } for k in (2, 4)},
        "fused_rounds[1@bf16]": {
            "dtypes_present": ["bf16", "f32"],
        },
        **{f"fused_rounds[{k}@bf16]": {
            "dtypes_present": ["bf16", "f32"],
            "collective_bytes_scale": {"vs": "fused_rounds[1@bf16]",
                                       "rounds": k},
           } for k in (2, 4)},
    },
    "cohort_rounds": {
        f"cohort_rounds[n{n}]": {
            "collective_bytes_independent": {"vs": "cohort_rounds[n16]"},
        } for n in (32, 64)
    },
    "parallel_fedavg": {
        "fedavg[weighted_delta_bf16]": {
            "dtypes_present": ["bf16", "f32"],
            "max_collective_bytes_ratio": {
                "vs": "fedavg[weighted_psum]", "ratio": 0.6},
        },
        **{f"robust_agg[{a}@bf16]": {
            "dtypes_present": ["bf16", "f32"],
            "max_collective_bytes_ratio": {
                "vs": f"robust_agg[{a}]",
                # psum aggregators: gate scalars dominate the toy payload
                # (measured 0.81); gather aggregators ship the bulk leaves
                # at bf16 (measured 0.58)
                "ratio": 0.85 if a in ("weighted", "clipped") else 0.65},
           } for a in ("weighted", "clipped", "trimmed", "median")},
    },
    "ingest": {
        # the onboarding programs are single-device batch dispatches: any
        # collective appearing (or growing with the population) means the
        # ingest path started shipping per-client traffic again
        "ingest_fit[b64xr128]": {
            "collective_bytes_independent": {"vs": "ingest_fit[b8xr128]"},
        },
        "ingest_wd[n64]": {
            "collective_bytes_independent": {"vs": "ingest_wd[n8]"},
        },
    },
    "serve_engine": {
        # donation_required: every serve bucket writes into a DONATED
        # output scratch — losing the tf.aliasing_output/jax.buffer_donor
        # alias (e.g. the scratch going unused and getting DCE'd, or a
        # refactor dropping donate_argnums) re-allocates output per
        # dispatch in steady state, which is a REGRESSION, not drift
        **{serve_bucket_name(n, c): {"donation_required": 1}
           for n in (1, 4) for c in (False, True)},
        **{serve_bucket_name(n, c, "bf16"): {
            "dtypes_present": ["bf16", "f32"],
            "donation_required": 1,
           } for n in (1, 4) for c in (False, True)},
        **{fleet_bucket_name(n, c, lanes=2): {"donation_required": 1}
           for n in (1, 4) for c in (False, True)},
    },
}


def lower_fingerprints(
    families: Optional[Dict[str, Dict[str, Callable]]] = None,
) -> Dict[str, Dict[str, Fingerprint]]:
    """Lower every entrypoint and fingerprint its StableHLO text.

    ``families`` overrides the registry (tests inject tiny programs); a
    builder may return a Lowered (``.as_text()``) or the text itself.
    """
    out: Dict[str, Dict[str, Fingerprint]] = {}
    for family, programs in (families or ENTRYPOINT_FAMILIES).items():
        out[family] = {}
        for name, build in programs.items():
            lowered = build()
            text = lowered if isinstance(lowered, str) else lowered.as_text()
            out[family][name] = fingerprint_text(text)
    return out
