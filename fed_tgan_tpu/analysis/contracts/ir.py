"""StableHLO/MLIR text -> structured program fingerprint.

Pure stdlib text analysis: lowering happens elsewhere (the harness);
this module only reads the ``.as_text()`` dump.  The extracted facts are
deliberately coarse -- op counts, byte totals, dtype tallies -- because
the contract diff must be stable across benign refactors yet catch the
three silent cost regressions jaxlint structurally cannot see:

* an extra collective (all_gather/all_reduce/...) or a fatter payload;
* a bigger host<->device transfer surface (more/larger main() operands
  or results, lost donation aliasing);
* a dtype promotion (f64 creeping into an f32 program).

Parsing notes (verified against jax 0.4.x StableHLO dumps):

* collectives appear as ``"stablehlo.all_reduce"(...)``; ops with a
  reduction region close with ``}) : (operand types) -> result type``
  while single-line ops carry the signature inline.  Region bodies never
  contain ``->``, so the first ``-> <type>`` after the op name is that
  op's own result signature.
* ``func.func public @main(...)`` declares the program's transfer
  surface; donated operands carry ``tf.aliasing_output`` /
  ``jax.buffer_donor`` arg attributes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

__all__ = ["Fingerprint", "fingerprint_text", "tensor_nbytes",
           "total_collective_bytes"]

#: bytes per element for the dtypes XLA emits; unknown dtypes count as 0
#: bytes (they still show in the census, so a contract catches them).
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1,
    "i4": 1, "ui4": 1, "i1": 1,
    "c64": 8, "c128": 16,
}

#: the cross-device communication ops a contract ratchets.
COLLECTIVE_OPS = (
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "collective_permute",
    "collective_broadcast",
)

_TENSOR_RE = re.compile(r"tensor<([0-9x]*)([A-Za-z][A-Za-z0-9]*)>")
_COLLECTIVE_RE = re.compile(
    r'"(?:stablehlo|mhlo)\.(%s)"' % "|".join(COLLECTIVE_OPS))
#: an op's function-type signature: single result or a result tuple.
_ARROW_RE = re.compile(r"->\s*(\([^)]*\)|tensor<[^>]+>)")
_MAIN_RE = re.compile(r"func\.func\s+(?:public\s+)?@main\(")
_DONATION_RE = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")


def tensor_nbytes(dims: str, dtype: str) -> int:
    """Byte size of one ``tensor<DIMSxDTYPE>`` occurrence."""
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 0)


def _types_bytes(fragment: str) -> Tuple[int, int]:
    """(tensor count, total bytes) over every tensor type in ``fragment``."""
    count = total = 0
    for dims, dtype in _TENSOR_RE.findall(fragment):
        count += 1
        total += tensor_nbytes(dims, dtype)
    return count, total


@dataclass
class Fingerprint:
    """The contract-relevant shape of one lowered program."""

    #: op name -> {"count": occurrences, "bytes": summed result bytes}
    collectives: Dict[str, Dict[str, int]] = field(default_factory=dict)
    #: n_inputs / in_bytes / n_outputs / out_bytes / donated_args
    transfers: Dict[str, int] = field(default_factory=dict)
    #: dtype -> number of tensor-type occurrences in the module text
    dtypes: Dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "collectives": {k: dict(v) for k, v in
                            sorted(self.collectives.items())},
            "transfers": dict(sorted(self.transfers.items())),
            "dtypes": dict(sorted(self.dtypes.items())),
        }

    @classmethod
    def from_dict(cls, data: dict) -> "Fingerprint":
        return cls(
            collectives={k: dict(v) for k, v in
                         data.get("collectives", {}).items()},
            transfers=dict(data.get("transfers", {})),
            dtypes=dict(data.get("dtypes", {})),
        )


def total_collective_bytes(fp: "Fingerprint") -> int:
    """Summed payload bytes over every collective op of one program — the
    quantity the bf16 contracts' ``max_collective_bytes_ratio`` requirement
    bounds against the f32 twin program."""
    return sum(int(entry.get("bytes", 0))
               for entry in fp.collectives.values())


def _split_top_level(s: str) -> List[str]:
    """Split on commas outside (), [], {} and <> nesting."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "([{<":
            depth += 1
        elif ch in ")]}>":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    tail = s[start:].strip()
    if tail:
        parts.append(tail)
    return [p for p in (p.strip() for p in parts) if p]


def _main_signature(text: str) -> Tuple[str, str]:
    """(argument list, result fragment) of ``@main``, or ("", "")."""
    m = _MAIN_RE.search(text)
    if not m:
        return "", ""
    i = m.end()  # just past the opening paren of the arg list
    depth = 1
    j = i
    while j < len(text) and depth:
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
        j += 1
    args = text[i:j - 1]
    # optional "-> <results>" between the arg list and the body brace
    rest = text[j:]
    brace = rest.find("{")
    head = rest[:brace if brace >= 0 else len(rest)]
    arrow = head.find("->")
    results = head[arrow + 2:] if arrow >= 0 else ""
    # a result list "(type {attrs}, ...)" re-opens parens; take through
    # the matching close so multi-result programs keep every entry
    if arrow >= 0 and "(" in results:
        k = rest.find("(", arrow)
        depth, e = 0, k
        while e < len(rest):
            if rest[e] == "(":
                depth += 1
            elif rest[e] == ")":
                depth -= 1
                if depth == 0:
                    break
            e += 1
        results = rest[k:e + 1]
    return args, results


def fingerprint_text(text: str) -> Fingerprint:
    """Walk one module's StableHLO text into a :class:`Fingerprint`."""
    fp = Fingerprint()

    # ------------------------------------------------------- collectives
    for m in _COLLECTIVE_RE.finditer(text):
        op = m.group(1)
        entry = fp.collectives.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        # region bodies contain no "->", so the first arrow after the op
        # name is this op's own (operands) -> results signature
        sig = _ARROW_RE.search(text, m.end())
        if sig:
            _, nbytes = _types_bytes(sig.group(1))
            entry["bytes"] += nbytes

    # --------------------------------------------------------- transfers
    args, results = _main_signature(text)
    n_in = in_bytes = donated = 0
    for arg in _split_top_level(args):
        c, b = _types_bytes(arg)
        n_in += c
        in_bytes += b
        if _DONATION_RE.search(arg):
            donated += 1
    n_out, out_bytes = _types_bytes(results)
    fp.transfers = {
        "n_inputs": n_in,
        "in_bytes": in_bytes,
        "n_outputs": n_out,
        "out_bytes": out_bytes,
        "donated_args": donated,
    }

    # ------------------------------------------------------ dtype census
    for _, dtype in _TENSOR_RE.findall(text):
        fp.dtypes[dtype] = fp.dtypes.get(dtype, 0) + 1
    return fp
