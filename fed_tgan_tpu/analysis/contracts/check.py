"""Contract persistence, two-sided diffing, and the CLI policy.

One JSON file per entrypoint family lives next to this module (e.g.
``train_federated.json``); each holds the recorded
:class:`~fed_tgan_tpu.analysis.contracts.ir.Fingerprint` per program
plus the family's forbidden-dtype list.  The diff is a TWO-SIDED
ratchet:

* **regression** (exit 1): a collective op appeared or grew (count or
  payload bytes), the host<->device transfer surface grew, donation
  aliasing was lost, a forbidden dtype (f64 by default) crept in, a
  contracted program vanished from the harness, or a new program has no
  contract;
* **improvement** (exit 0 + stale-contract warning): the same metrics
  moved the *good* way -- the contract is stale and should be
  re-recorded with ``--contracts-update`` so the better number becomes
  the new ceiling;
* **drift** (exit 0, informational): benign census changes (non-
  forbidden dtype tallies).

``--explain`` augments each regression with the op delta and candidate
source sites grepped from the family's subsystem directories.
Exit codes: 0 clean/improved, 1 regression, 2 lowering unavailable or
unreadable contracts.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional

from fed_tgan_tpu.analysis.contracts.harness import (
    ENTRYPOINT_FAMILIES,
    HarnessError,
    PROGRAM_REQUIREMENTS,
    lower_fingerprints,
)
from fed_tgan_tpu.analysis.contracts.ir import (
    Fingerprint,
    total_collective_bytes,
)

__all__ = [
    "CONTRACTS_DIR",
    "ContractError",
    "Issue",
    "check_requirements",
    "diff_contracts",
    "load_contracts",
    "run_contracts",
    "save_contracts",
]

CONTRACTS_DIR = Path(__file__).resolve().parent
DEFAULT_FORBID_DTYPES = ("f64",)

REGRESSION = "regression"
IMPROVEMENT = "improvement"
DRIFT = "drift"


class ContractError(RuntimeError):
    """Unreadable / malformed contract file (CLI exit code 2)."""


@dataclass
class Issue:
    severity: str  # regression | improvement | drift
    family: str
    program: str
    metric: str    # e.g. "collectives.all_gather.count"
    old: object
    new: object
    message: str
    sites: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"severity": self.severity, "family": self.family,
                "program": self.program, "metric": self.metric,
                "old": self.old, "new": self.new,
                "message": self.message, "sites": self.sites}

    def render(self, explain: bool = False) -> str:
        head = (f"{self.severity.upper()} {self.family}/{self.program}: "
                f"{self.metric} {self.old} -> {self.new} ({self.message})")
        if explain and self.sites:
            head += "\n    candidate source sites:" + "".join(
                f"\n      {s}" for s in self.sites)
        return head


# --------------------------------------------------------------- storage

def _family_path(family: str, contracts_dir: Optional[Path] = None) -> Path:
    return Path(contracts_dir or CONTRACTS_DIR) / f"{family}.json"


def load_contracts(families, contracts_dir: Optional[Path] = None
                   ) -> Dict[str, Optional[dict]]:
    """family -> {"programs": {...}, "forbid_dtypes": [...]} or None when
    the family has no contract file yet."""
    out: Dict[str, Optional[dict]] = {}
    for family in families:
        path = _family_path(family, contracts_dir)
        if not path.exists():
            out[family] = None
            continue
        try:
            data = json.loads(path.read_text())
            data["programs"]  # noqa: B018 -- shape check
        except (OSError, json.JSONDecodeError, KeyError) as exc:
            raise ContractError(f"bad contract {path}: {exc!r}") from exc
        out[family] = data
    return out


def save_contracts(current: Dict[str, Dict[str, Fingerprint]],
                   contracts_dir: Optional[Path] = None) -> List[Path]:
    paths = []
    for family, programs in sorted(current.items()):
        # requirement blocks come from the code-side registry, never from
        # the old JSON: --contracts-update regenerates them, and programs
        # the registry doesn't name (tests' toy entrypoints) get none
        reqs = PROGRAM_REQUIREMENTS.get(family, {})
        entries = {}
        for name, fp in sorted(programs.items()):
            entry = fp.to_dict()
            if name in reqs:
                entry["require"] = reqs[name]
            entries[name] = entry
        payload = {
            "version": 1,
            "comment": ("lowered-HLO program contract; regenerate with "
                        "python -m fed_tgan_tpu.analysis "
                        "--contracts-update"),
            "forbid_dtypes": list(DEFAULT_FORBID_DTYPES),
            "programs": entries,
        }
        path = _family_path(family, contracts_dir)
        # per-backend contract sets live in subdirectories of the default
        # dir (runtime.backend.contracts_dir_for); create on first record
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(payload, indent=2, sort_keys=True)
                        + "\n")
        paths.append(path)
    return paths


# ------------------------------------------------------------------ diff

def _metric_issue(family, program, metric, old, new, grow_is_bad,
                  what) -> Optional[Issue]:
    if new == old:
        return None
    grew = new > old
    bad = grew == grow_is_bad
    delta = new - old
    return Issue(
        severity=REGRESSION if bad else IMPROVEMENT,
        family=family, program=program, metric=metric, old=old, new=new,
        message=f"{'+' if delta > 0 else ''}{delta} {what}",
    )


def diff_program(family: str, program: str, stored: dict,
                 current: Fingerprint,
                 forbid_dtypes=DEFAULT_FORBID_DTYPES) -> List[Issue]:
    issues: List[Issue] = []
    add = issues.append

    # ------------------------------------------------------- collectives
    old_c = stored.get("collectives", {})
    new_c = current.collectives
    for op in sorted(set(old_c) | set(new_c)):
        o = old_c.get(op, {"count": 0, "bytes": 0})
        n = new_c.get(op, {"count": 0, "bytes": 0})
        for f, what in (("count", f"{op} op(s)"),
                        ("bytes", f"{op} payload byte(s)")):
            iss = _metric_issue(family, program,
                               f"collectives.{op}.{f}",
                               o.get(f, 0), n.get(f, 0),
                               grow_is_bad=True, what=what)
            if iss:
                add(iss)

    # --------------------------------------------------------- transfers
    old_t = stored.get("transfers", {})
    new_t = current.transfers
    for f, what in (("n_inputs", "program input(s)"),
                    ("in_bytes", "input byte(s)"),
                    ("n_outputs", "program output(s)"),
                    ("out_bytes", "output byte(s)")):
        iss = _metric_issue(family, program, f"transfers.{f}",
                           old_t.get(f, 0), new_t.get(f, 0),
                           grow_is_bad=True, what=what)
        if iss:
            add(iss)
    # donation aliasing saves a transfer: LOSING it is the regression
    iss = _metric_issue(family, program, "transfers.donated_args",
                       old_t.get("donated_args", 0),
                       new_t.get("donated_args", 0),
                       grow_is_bad=False, what="donated operand(s)")
    if iss:
        add(iss)

    # ------------------------------------------------------------ dtypes
    old_d = stored.get("dtypes", {})
    new_d = current.dtypes
    for dt in sorted(set(old_d) | set(new_d)):
        o, n = old_d.get(dt, 0), new_d.get(dt, 0)
        if o == n:
            continue
        if dt in forbid_dtypes:
            iss = _metric_issue(family, program, f"dtypes.{dt}", o, n,
                               grow_is_bad=True,
                               what=f"{dt} tensor type(s) "
                                    f"({dt} is forbidden here)")
            if iss:
                add(iss)
        else:
            add(Issue(severity=DRIFT, family=family, program=program,
                      metric=f"dtypes.{dt}", old=o, new=n,
                      message=f"{dt} census moved (informational)"))
    return issues


def check_requirements(family: str, program: str, require: dict,
                       programs: Dict[str, Fingerprint]) -> List[Issue]:
    """Evaluate one contract's ``require`` block against the CURRENT
    family fingerprints (unlike the ratchet, which diffs old vs new,
    a requirement is an absolute property the program must keep).

    * ``dtypes_present``: each listed dtype must appear in the census —
      how the bf16 contracts pin both the compute cast (bf16) and the
      f32 islands (f32);
    * ``donation_required``: at least N donated-argument aliases
      (``tf.aliasing_output`` / ``jax.buffer_donor``) must survive
      lowering — the serve buckets' donated output scratch is a steady-
      state allocation contract, and a lost alias is a REGRESSION;
    * ``max_collective_bytes_ratio {vs, ratio}``: total collective bytes
      must stay <= ratio * the named sibling program's total — the
      "~2x lower aggregation payload" criterion, immune to both programs
      drifting together;
    * ``collective_bytes_scale {vs, rounds}``: IR collective bytes must
      EQUAL the named single-round sibling's.  Collectives inside the
      round scan lower once regardless of length, so equality is exactly
      the statement "logical collective traffic scales ``rounds`` × the
      single-round program": IR totals growing means the scan unrolled
      into per-round collectives, any other delta means the per-round
      aggregation payload re-widened;
    * ``collective_bytes_independent {vs}``: IR collective bytes must
      EQUAL the named smallest-population sibling's.  Under cohort
      sampling the round payload is O(cohort) + O(model) regardless of
      how many client shards are resident, so the lowered collective
      totals must not move as the population N grows at fixed cohort C.
    """
    issues: List[Issue] = []
    fp = programs[program]
    for dt in require.get("dtypes_present", ()):
        if fp.dtypes.get(dt, 0) <= 0:
            issues.append(Issue(
                severity=REGRESSION, family=family, program=program,
                metric=f"require.dtypes_present.{dt}",
                old="present", new="absent",
                message=f"required dtype {dt} vanished from the lowered "
                        "program (precision policy no longer applied?)"))
    donation_req = require.get("donation_required")
    if donation_req:
        donated = int(fp.transfers.get("donated_args", 0))
        if donated < int(donation_req):
            issues.append(Issue(
                severity=REGRESSION, family=family, program=program,
                metric="require.donation_required",
                old=f">= {int(donation_req)} donated arg(s)", new=donated,
                message="output-scratch donation alias vanished from the "
                        "lowered program (donate_argnums dropped, or the "
                        "donated buffer went unused and was DCE'd) -- "
                        "steady-state serving re-allocates output per "
                        "dispatch"))
    ratio_req = require.get("max_collective_bytes_ratio")
    if ratio_req:
        vs, ratio = ratio_req["vs"], float(ratio_req["ratio"])
        if vs not in programs:
            issues.append(Issue(
                severity=REGRESSION, family=family, program=program,
                metric="require.max_collective_bytes_ratio",
                old=vs, new="missing",
                message="baseline program for the payload-ratio "
                        "requirement is no longer lowered"))
        else:
            mine = total_collective_bytes(fp)
            base = total_collective_bytes(programs[vs])
            if mine > ratio * base:
                issues.append(Issue(
                    severity=REGRESSION, family=family, program=program,
                    metric="require.max_collective_bytes_ratio",
                    old=f"<= {ratio} x {base} ({vs})", new=mine,
                    message="reduced-precision program lost its "
                            "collective-payload advantage over the f32 "
                            "twin"))
    scale_req = require.get("collective_bytes_scale")
    if scale_req:
        vs, k_rounds = scale_req["vs"], int(scale_req["rounds"])
        if vs not in programs:
            issues.append(Issue(
                severity=REGRESSION, family=family, program=program,
                metric="require.collective_bytes_scale",
                old=vs, new="missing",
                message="single-round baseline for the scan-over-rounds "
                        "requirement is no longer lowered"))
        else:
            mine = total_collective_bytes(fp)
            base = total_collective_bytes(programs[vs])
            if mine != base:
                hint = ("round scan unrolled into per-round collectives?"
                        if base and mine >= k_rounds * base
                        else "per-round aggregation payload re-widened?")
                issues.append(Issue(
                    severity=REGRESSION, family=family, program=program,
                    metric="require.collective_bytes_scale",
                    old=f"== {base} ({vs})", new=mine,
                    message=f"IR collective bytes must equal the single-"
                            f"round program so logical traffic scales "
                            f"exactly {k_rounds}x ({hint})"))
    indep_req = require.get("collective_bytes_independent")
    if indep_req:
        vs = indep_req["vs"]
        if vs not in programs:
            issues.append(Issue(
                severity=REGRESSION, family=family, program=program,
                metric="require.collective_bytes_independent",
                old=vs, new="missing",
                message="smallest-population baseline for the cohort "
                        "N-independence requirement is no longer lowered"))
        else:
            mine = total_collective_bytes(fp)
            base = total_collective_bytes(programs[vs])
            if mine != base:
                issues.append(Issue(
                    severity=REGRESSION, family=family, program=program,
                    metric="require.collective_bytes_independent",
                    old=f"== {base} ({vs})", new=mine,
                    message="cohort-round collective bytes must be "
                            "independent of the client population N at "
                            "fixed cohort C (accidental all_gather/psum "
                            "over the population axis?)"))
    return issues


def diff_contracts(current: Dict[str, Dict[str, Fingerprint]],
                   stored: Dict[str, Optional[dict]]) -> List[Issue]:
    issues: List[Issue] = []
    for family, programs in sorted(current.items()):
        fam = stored.get(family)
        if fam is None:
            issues.append(Issue(
                severity=REGRESSION, family=family, program="*",
                metric="contract", old="missing", new=f"{len(programs)} "
                "program(s)",
                message="no contract file; record one with "
                        "--contracts-update"))
            continue
        recorded = fam.get("programs", {})
        forbid = tuple(fam.get("forbid_dtypes", DEFAULT_FORBID_DTYPES))
        for name in sorted(set(recorded) | set(programs)):
            if name not in programs:
                issues.append(Issue(
                    severity=REGRESSION, family=family, program=name,
                    metric="contract", old="recorded", new="missing",
                    message="contracted entrypoint no longer lowered by "
                            "the harness (renamed? update the contract)"))
            elif name not in recorded:
                issues.append(Issue(
                    severity=REGRESSION, family=family, program=name,
                    metric="contract", old="missing", new="present",
                    message="new entrypoint without a contract; record "
                            "it with --contracts-update"))
            else:
                issues.extend(diff_program(family, name, recorded[name],
                                           programs[name], forbid))
                require = recorded[name].get("require")
                if require:
                    issues.extend(check_requirements(
                        family, name, require, programs))
    return issues


# --------------------------------------------------------------- explain

#: where each family's program logic lives -- the grep scope for
#: candidate source sites of a regression.
_FAMILY_DIRS = {
    "train_federated": ("train", "parallel", "ops", "models"),
    "fused_rounds": ("train", "parallel", "ops", "models"),
    "cohort_rounds": ("train", "parallel", "ops", "models"),
    "parallel_fedavg": ("parallel",),
    "ingest": ("features", "federation"),
    "serve_engine": ("serve", "ops", "models"),
}

_SITE_PATTERNS = {
    "collectives": re.compile(
        r"all_gather|psum|pmin|pmax|all_to_all|ppermute|reduce_scatter"
        r"|weighted_average|robust_aggregate"),
    "transfers": re.compile(
        r"device_get|device_put|copy_to_host_async|block_until_ready"
        r"|np\.asarray"),
    "dtypes": re.compile(r"float64|f64|astype\(\s*float\s*\)"),
}

_MAX_SITES = 5


def _candidate_sites(issue: Issue) -> List[str]:
    kind = issue.metric.split(".", 1)[0]
    pattern = _SITE_PATTERNS.get(kind)
    if pattern is None:
        return []
    from fed_tgan_tpu.analysis.lint import PKG_ROOT, REPO_ROOT

    dirs = _FAMILY_DIRS.get(issue.family, ())
    roots = [PKG_ROOT / d for d in dirs if (PKG_ROOT / d).is_dir()]
    sites: List[str] = []
    for root in roots or [PKG_ROOT]:
        for path in sorted(root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            try:
                lines = path.read_text().splitlines()
            except OSError:
                continue
            rel = path.relative_to(REPO_ROOT).as_posix()
            for i, line in enumerate(lines, 1):
                if pattern.search(line):
                    sites.append(f"{rel}:{i}: {line.strip()[:90]}")
                    if len(sites) >= _MAX_SITES:
                        return sites
    return sites


# ------------------------------------------------------------ run policy

def run_contracts(update: bool = False, explain: bool = False,
                  fmt: str = "text",
                  contracts_dir: Optional[Path] = None,
                  entrypoints: Optional[Dict[str, Dict[str, Callable]]]
                  = None,
                  out: Callable[[str], None] = print) -> int:
    """Lower, diff (or re-record), report.  Returns the exit code."""
    try:
        current = lower_fingerprints(entrypoints)
    except HarnessError as exc:
        out(f"contracts: lowering unavailable: {exc}")
        return 2

    if update:
        paths = save_contracts(current, contracts_dir)
        n = sum(len(p) for p in current.values())
        out(f"contracts: recorded {n} program fingerprint(s) across "
            f"{len(current)} family(ies) -> "
            + ", ".join(str(p) for p in paths))
        return 0

    try:
        stored = load_contracts(current, contracts_dir)
    except ContractError as exc:
        out(f"contracts: {exc}")
        return 2
    issues = diff_contracts(current, stored)
    regressions = [i for i in issues if i.severity == REGRESSION]
    improvements = [i for i in issues if i.severity == IMPROVEMENT]
    drift = [i for i in issues if i.severity == DRIFT]
    if explain:
        for i in regressions:
            i.sites = _candidate_sites(i)

    if fmt == "json":
        out(json.dumps({
            "families": {fam: sorted(progs) for fam, progs in
                         current.items()},
            "issues": [i.to_dict() for i in issues],
            "regressions": len(regressions),
            "improvements": len(improvements),
        }, indent=2))
        return 1 if regressions else 0

    for i in regressions:
        out(i.render(explain=explain))
    for i in improvements:
        out(i.render() + "\n    stale contract: re-record the better "
            "number with --contracts-update")
    for i in drift:
        out(i.render())
    n_prog = sum(len(p) for p in current.values())
    out(f"contracts: {n_prog} program(s) across {len(current)} "
        f"family(ies): {len(regressions)} regression(s), "
        f"{len(improvements)} improvement(s) (stale contracts), "
        f"{len(drift)} census drift(s)")
    return 1 if regressions else 0
