#!/bin/bash
# weighted-vs-uniform aggregation under Dirichlet label skew (VERDICT r3 #2)
cd /root/repo
for alpha in 0.1 0.5 2.0; do
  for mode in "" "--uniform"; do
    echo "=== alpha=$alpha mode=${mode:-weighted} $(date -u +%H:%M:%S)" >> noniid_out/sweep.log
    python bench.py --workload utility --epochs 500 --clients 8 \
      --shard-strategy dirichlet --alpha $alpha $mode --backend cpu \
      2>>noniid_out/sweep.log | tail -1 >> noniid_out/sweep_results.jsonl
  done
done
echo "SWEEP DONE $(date -u +%H:%M:%S)" >> noniid_out/sweep.log
